"""Kernel & goodput observatory (ISSUE 14): per-HLO census + roofline
placement over the committed demo fixture, compile-ledger join, fusion
forensics on the seeded quantize-boundary fusion, and the training
goodput ledger — lease nesting, states-sum-to-wall, the chaos-elastic
attribution gate, the fleet rollup, the off-path cost bound, and the
`tools/kernelscope.py --demo` meta-gate."""
import json
import logging
import os
import subprocess
import sys
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np, preemption
from incubator_mxnet_tpu.fault import injection
from incubator_mxnet_tpu.telemetry import goodput, kernels, registry, tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "benchmark", "kernelscope_demo_trace.json")


def _fixture():
    with open(FIXTURE, encoding="utf-8") as f:
        return json.load(f)


def _counter(name):
    return registry.report().get(name, {}).get("value", 0) or 0


@pytest.fixture(autouse=True)
def _clean_observatory():
    goodput.disable()
    goodput.reset()
    kernels.reset()
    yield
    goodput.disable()
    goodput.reset()
    kernels.reset()
    injection.clear_injection()


def _rows_by_name(result):
    return {r["name"]: r for r in result["rows"]}


# ---------------------------------------------------------------------------
# census: roofline placement + honest coverage (the committed fixture)
# ---------------------------------------------------------------------------

def test_census_fixture_roofline_placement():
    doc = _fixture()
    res = kernels.census(doc["before"]["traceEvents"], device="v5e")
    rows = _rows_by_name(res)

    # fusion.1: 4 x 1000 µs, 250 MB + 1e11 flops each -> 250 GB/s,
    # 100 TFLOP/s; flops_frac 100/197 beats hbm_frac 250/819 -> compute
    f1 = rows["fusion.1"]
    assert f1["count"] == 4 and f1["time_us"] == pytest.approx(4000.0)
    assert f1["achieved_gbs"] == pytest.approx(250.0)
    assert f1["achieved_tflops"] == pytest.approx(100.0)
    assert f1["bound_by"] == "compute"

    # fusion.2: 8 x 300 µs, 180 MB each -> 600 GB/s, 73% of the 819
    # GB/s v5e roof with negligible flops -> memory
    f2 = rows["fusion.2"]
    assert f2["achieved_gbs"] == pytest.approx(600.0)
    assert f2["hbm_frac"] == pytest.approx(600.0 / 819.0)
    assert f2["bound_by"] == "memory"

    # quantize/dequantize boundaries are present pre-fusion
    assert rows["quantize.1"]["count"] == 8
    assert rows["dequantize.1"]["bound_by"] == "memory"


def test_census_meta_attribution_is_honest():
    doc = _fixture()
    res = kernels.census(doc["before"]["traceEvents"], device="v5e")
    meta = res["meta"]
    # runtime lanes (tsl::AsyncExec, program_interpreter) count toward
    # total device time but are excluded from the named rows: 8720 µs
    # named of 9520 µs total
    assert meta["total_device_us"] == pytest.approx(9520.0)
    assert meta["named_us"] == pytest.approx(8720.0)
    assert meta["attributed_frac"] == pytest.approx(8720.0 / 9520.0)
    assert "tsl::AsyncExec" not in _rows_by_name(res)
    # 30 of 32 named events carry a bytes stat (convert.1 doesn't)
    assert meta["bytes_coverage"] == pytest.approx(30.0 / 32.0)
    # census parks its meta for the flight-context block
    assert kernels.last_census()["attributed_frac"] == pytest.approx(
        meta["attributed_frac"])


def test_census_unknown_bytes_never_reads_fast():
    doc = _fixture()
    res = kernels.census(doc["before"]["traceEvents"], device="v5e")
    conv = _rows_by_name(res)["convert.1"]
    # no bytes stat: no bandwidth claim, no roofline verdict
    assert conv["bytes_known"] == 0
    assert conv["achieved_gbs"] is None
    assert conv["bound_by"] == "unknown"
    # ...and it is excluded from the fusion-target ranking (never
    # ranked as fast OR slow)
    bb = kernels.top_bandwidth_bound(res, n=10)
    names = [r["name"] for r in bb]
    assert "convert.1" not in names and "fusion.1" not in names
    # ranking is by device time: fusion.2 dominates
    assert names[0] == "fusion.2"
    assert all(r["bound_by"] == "memory" for r in bb)


def test_census_ledger_join_balance_point():
    doc = _fixture()
    res = kernels.census(doc["before"]["traceEvents"],
                         ledger=doc["ledger"], device="v5e")
    progs = res["programs"]
    balance = 197e12 / 819e9          # v5e machine balance, flop/B
    train = progs["train.DataParallel.step"]
    assert train["balance_flops_per_byte"] == pytest.approx(balance)
    # AI 400 flop/B > 240.5 -> compute-bound per the cost model
    assert train["arith_intensity"] == pytest.approx(400.0)
    assert train["bound_by"] == "compute"
    assert train["compiles"] == 2
    # eager.dot: AI ~82 flop/B < balance -> memory-bound
    assert progs["eager.dot"]["bound_by"] == "memory"


def test_program_mfu_math_and_honesty():
    # 2.4e12 flops x 10 executions over 1 s on a 197 TFLOP/s chip
    mfu = kernels.program_mfu(2.4e12, 10, 1.0, device="v5e")
    assert mfu == pytest.approx(2.4e13 / 197e12)
    # the honesty rule: any missing input -> None, never a guess
    assert kernels.program_mfu(None, 10, 1.0, device="v5e") is None
    assert kernels.program_mfu(2.4e12, 0, 1.0, device="v5e") is None
    assert kernels.program_mfu(2.4e12, 10, 0.0, device="v5e") is None
    assert kernels.program_mfu(2.4e12, 10, 1.0) is None  # no peak known


def test_census_over_live_profiler_trace():
    from incubator_mxnet_tpu import profiler

    a = np.ones((64, 64))
    (np.dot(a, a) + 1.0).asnumpy()          # compile outside the window
    profiler.start()
    (np.dot(a, a) + 1.0).asnumpy()
    profiler.stop()
    res = kernels.census(profiler.device_events(), device="v5e")
    meta = res["meta"]
    assert meta["total_device_us"] > 0
    assert 0.0 <= meta["attributed_frac"] <= 1.0
    # CPU traces carry no per-kernel byte stats: everything must read
    # unknown, nothing may claim a roofline placement
    assert all(r["bound_by"] == "unknown" for r in res["rows"]
               if not r["bytes_known"])


# ---------------------------------------------------------------------------
# fusion forensics
# ---------------------------------------------------------------------------

def test_diff_census_names_seeded_fusion(tmp_path):
    doc = _fixture()
    before = kernels.census(doc["before"]["traceEvents"], device="v5e")
    after = kernels.census(doc["after"]["traceEvents"], device="v5e")
    v0 = _counter('mx_kernel_fusion_delta{kind="vanished"}')

    diff = kernels.diff_census(before, after)
    # the quantize boundaries vanished into the consumer fusion
    assert diff["vanished"] == ["dequantize.1", "quantize.1"]
    assert diff["appeared"] == [] and diff["split"] == []
    assert diff["verdict"] == "fused"
    # 8720 µs named before, 6480 after: the fusion bought 2240 µs
    assert diff["time_delta_us"] == pytest.approx(-2240.0)
    # the delta is a series...
    assert _counter('mx_kernel_fusion_delta{kind="vanished"}') == v0 + 2
    # ...and rides every flight record via the context probe
    path = tracing.flight_dump("test_fusion",
                               path=str(tmp_path / "flight.json"))
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    blk = payload["context"]["kernels"]
    assert blk["fusion_delta"]["verdict"] == "fused"
    assert blk["census"]["n_kernels"] == after["meta"]["n_kernels"]


def test_diff_census_split_and_unchanged():
    rows = [{"name": "fusion.1", "time_us": 10.0}]
    two = [{"name": "fusion.1", "time_us": 6.0},
           {"name": "fusion.2", "time_us": 6.0}]
    d = kernels.diff_census(rows, two)
    assert d["verdict"] == "split" and d["split"] == ["fusion"]
    assert kernels.diff_census(rows, rows)["verdict"] == "unchanged"


def test_format_census_and_diff_render():
    doc = _fixture()
    res = kernels.census(doc["before"]["traceEvents"],
                         ledger=doc["ledger"], device="v5e")
    s = kernels.format_census(res, top=5)
    assert "fusion.1" in s and "bound by" in s
    assert "never *fast*" in s                  # the honesty footnote
    assert "program `train.DataParallel.step`" in s
    after = kernels.census(doc["after"]["traceEvents"], device="v5e")
    d = kernels.format_diff(kernels.diff_census(res, after))
    assert "fusion delta: fused" in d
    assert "vanished: dequantize.1, quantize.1" in d


# ---------------------------------------------------------------------------
# goodput ledger: lease semantics
# ---------------------------------------------------------------------------

def test_goodput_states_sum_to_wall():
    goodput.enable()
    with goodput.lease("compute"):
        time.sleep(0.05)
    time.sleep(0.02)                            # unleased -> idle
    with goodput.lease("data_wait"):
        time.sleep(0.01)
    rep = goodput.report()
    assert rep["enabled"] and rep["active_lease"] is None
    # idle is a real state, so the states sum to wall EXACTLY
    assert sum(rep["states"].values()) == pytest.approx(
        rep["wall_s"], rel=1e-9)
    assert rep["states"]["compute"] >= 0.05
    assert rep["states"]["data_wait"] >= 0.01
    assert rep["states"]["idle"] >= 0.015
    assert rep["accounted_s"] == pytest.approx(
        rep["wall_s"] - rep["states"]["idle"], rel=1e-9)
    assert 0.0 < rep["goodput_frac"] < 1.0


def test_goodput_nesting_innermost_wins():
    goodput.enable()
    with goodput.lease("reshard"):
        time.sleep(0.02)
        with goodput.lease("checkpoint"):       # e.g. drain checkpoint
            time.sleep(0.03)
        time.sleep(0.01)
    rep = goodput.report()
    # the inner lease takes its interval; the rest stays reshard
    assert rep["states"]["checkpoint"] >= 0.03
    assert rep["states"]["reshard"] >= 0.03
    assert rep["states"]["reshard"] < rep["wall_s"] - 0.025
    assert sum(rep["states"].values()) == pytest.approx(
        rep["wall_s"], rel=1e-9)


def test_goodput_series_and_pull_gauge():
    goodput.enable()
    c0 = _counter('mx_goodput_seconds_total{state="compute"}')
    with goodput.lease("compute"):
        time.sleep(0.03)
    rep = registry.report()
    key = 'mx_goodput_seconds_total{state="compute"}'
    assert key in rep and rep[key]["value"] >= c0 + 0.03
    gf = goodput.goodput_frac()                 # the pull-gauge probe
    assert gf is not None and 0.0 < gf <= 1.0


def test_goodput_off_is_null_and_unknown_state_raises():
    assert not goodput.is_enabled()
    # disabled: every lease is the SAME shared null context manager
    assert goodput.lease("compute") is goodput.lease("reshard")
    with goodput.lease("compute"):
        time.sleep(0.005)
    rep = goodput.report()
    assert rep["wall_s"] == 0.0 and not any(rep["states"].values())
    goodput.enable()
    with pytest.raises(ValueError, match="unknown goodput state"):
        goodput.lease("productive")
    # reset drops attribution and the ledger epoch
    with goodput.lease("compute"):
        pass
    goodput.reset()
    assert goodput.report()["wall_s"] == 0.0
    assert goodput.goodput_frac() is None       # honest: no epoch yet


def test_goodput_off_path_is_cheap():
    assert not goodput.is_enabled()
    a = np.array(onp.random.RandomState(0).uniform(-1, 1, (16, 16))
                 .astype("float32"))
    np.dot(a, a).wait_to_read()                 # warm the jit cache
    iters = 300
    t0 = time.perf_counter()
    for _ in range(iters):
        np.dot(a, a)
    mx.waitall()
    per_op = (time.perf_counter() - t0) / iters
    # the literal instrumented-seam pattern, disabled
    t0 = time.perf_counter()
    for _ in range(iters):
        with goodput.lease("compute"):
            pass
    probe = (time.perf_counter() - t0) / iters
    assert probe < 0.03 * per_op, (probe, per_op)


def test_goodput_waterfall_renders_fixture():
    rep = _fixture()["goodput"]
    s = goodput.format_waterfall(rep)
    assert "goodput waterfall" in s
    assert "goodput 80.8%" in s and "accounted 99.1%" in s
    for state in goodput.STATES:
        assert state in s


# ---------------------------------------------------------------------------
# goodput ledger: the real seams
# ---------------------------------------------------------------------------

def test_estimator_fit_feeds_the_ledger():
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator

    goodput.enable()
    X = np.random.uniform(size=(64, 4))
    Y = X @ np.random.uniform(size=(4, 1))
    loader = gluon.data.DataLoader(gluon.data.ArrayDataset(X, Y),
                                   batch_size=16)
    net = gluon.nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    est = Estimator(net, loss=gluon.loss.L2Loss(), trainer=trainer)
    est.logger.setLevel(logging.ERROR)
    est.fit(loader, epochs=2)
    rep = goodput.report()
    # the fit_batch seam leased compute; the dataloader leased data_wait
    assert rep["states"]["compute"] > 0.0
    assert rep["states"]["data_wait"] > 0.0
    assert rep["goodput_frac"] > 0.0
    assert sum(rep["states"].values()) == pytest.approx(
        rep["wall_s"], rel=1e-9)


def _make_dp(mesh, seed=0):
    from incubator_mxnet_tpu import optimizer as opt
    from incubator_mxnet_tpu.parallel import DataParallel

    mx.random.seed(seed)
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    dp = DataParallel(net, lambda o, y: ((o - y) ** 2),
                      opt.SGD(learning_rate=0.1), mesh=mesh)
    return net, dp


def test_goodput_chaos_elastic_attribution(tmp_path):
    """ISSUE 14 acceptance gate: across a chaos run with a seeded
    topology shrink plus a checkpoint/resume cycle, the ledger's states
    sum to wall within 2%, reshard and recovery are nonzero, and the
    fleet rollup carries the view."""
    from incubator_mxnet_tpu.fault.elastic import ElasticController
    from incubator_mxnet_tpu.parallel import dist
    from incubator_mxnet_tpu.parallel.mesh import make_mesh
    from incubator_mxnet_tpu.telemetry import fleet

    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 4)).astype("float32")
    Y = X @ rng.uniform(-1, 1, (4, 1)).astype("float32")

    dist._reset_membership()
    injection.clear_injection()
    net, dp = _make_dp(make_mesh({"dp": 8}))
    ctl = ElasticController(trainer=dp)
    goodput.enable()
    goodput.reset()
    injection.configure_injection("topology_change:1.0:11:1:shrink=4")
    for step in range(4):
        with goodput.lease("compute"):
            float(dp.step(X, Y))
        verdict = ctl.poll()                    # drained step boundary
        if step == 0:
            assert verdict == "shrunk"          # transition leased reshard
    injection.clear_injection()

    # the checkpoint write + resume seams lease checkpoint/recovery
    trainer = gluon.Trainer(net.collect_params(), "sgd")
    ck = preemption.TrainingCheckpointer(
        str(tmp_path / "ck"), net, trainer, every_n=1000, keep=2,
        register_signal=False)
    assert ck.save_now() is not None
    ck.resume()                                 # step 0: resumed fresh

    rep = goodput.report()
    states = rep["states"]
    assert states["compute"] > 0.0
    assert states["reshard"] > 0.0, states
    assert states["checkpoint"] > 0.0, states
    assert states["recovery"] > 0.0, states
    # every wall second attributed: within 2% of wall (exact by
    # construction; the tolerance is the acceptance claim)
    assert abs(sum(states.values()) - rep["wall_s"]) <= max(
        0.02 * rep["wall_s"], 1e-6), rep
    assert int(dp.mesh.devices.size) == 4       # the shrink really ran

    # fleet rollup: single-process fleet_report carries the ledger
    g = fleet.fleet_report()["goodput"]
    assert g is not None
    assert g["fleet_states"]["reshard"] > 0.0
    assert 0.0 <= g["fleet_goodput_frac"] <= 1.0
    assert 0 in g["per_rank"]
    assert g["worst_data_wait_rank"] == 0


def test_goodput_rides_flight_records(tmp_path):
    goodput.enable()
    with goodput.lease("compute"):
        time.sleep(0.01)
    path = tracing.flight_dump("test_goodput",
                               path=str(tmp_path / "flight.json"))
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    blk = payload["context"]["goodput"]
    assert blk["states"]["compute"] >= 0.01
    assert blk["goodput_frac"] > 0.0


# ---------------------------------------------------------------------------
# kernelscope CLI (the committed-artifact meta-gate)
# ---------------------------------------------------------------------------

def test_kernelscope_demo_renders():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "kernelscope.py"),
         "--demo"], capture_output=True, text=True, timeout=180, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "kernel census: before" in out.stdout
    assert "fusion delta: fused" in out.stdout
    assert "vanished: dequantize.1, quantize.1" in out.stdout
    assert "goodput waterfall" in out.stdout
    assert "unknown" in out.stdout              # convert.1 stays honest
