"""Funnel-level AMP + gradient compression tests (reference:
`tests/python/unittest/test_amp.py`, `tests/nightly/test_kvstore.py`
compression cases)."""
import numpy as onp
import pytest

import ml_dtypes

from incubator_mxnet_tpu import amp, autograd, gluon, np, npx
from incubator_mxnet_tpu.kvstore.compression import GradientCompression, create


@pytest.fixture
def amp_bf16():
    amp.init("bfloat16")
    yield
    amp.deinit()


def test_amp_target_ops_cast(amp_bf16):
    x = np.random.uniform(size=(4, 8))
    w = np.random.uniform(size=(8, 4))
    assert onp.dtype(np.dot(x, w).dtype) == onp.dtype(ml_dtypes.bfloat16)
    assert onp.dtype(np.matmul(x, w).dtype) == onp.dtype(ml_dtypes.bfloat16)


def test_amp_fp32_ops_upcast(amp_bf16):
    x = np.random.uniform(size=(4, 8)).astype("bfloat16")
    assert onp.dtype(npx.softmax(x).dtype) == onp.float32
    # layer_norm is dtype-PRESERVING (bf16 in -> bf16 out) with f32
    # internal statistics: under bf16 AMP the f32 up-cast would only add
    # HBM traffic since the next matmul casts back down
    out = npx.layer_norm(x, np.ones((8,)), np.zeros((8,)), axis=-1)
    assert onp.dtype(out.dtype) == onp.dtype("bfloat16")
    # f32 internal math: result must match the f32 reference to bf16 eps
    xf = x.astype("float32").asnumpy()
    mu = xf.mean(-1, keepdims=True)
    ref = (xf - mu) / onp.sqrt(xf.var(-1, keepdims=True) + 1e-5)
    assert onp.allclose(out.asnumpy().astype("float32"), ref,
                        atol=1e-2, rtol=1e-2)


def test_amp_grads_stay_f32(amp_bf16):
    x = np.random.uniform(size=(4, 8))
    w = np.random.uniform(size=(8, 4))
    x.attach_grad()
    with autograd.record():
        out = np.dot(x, w).sum()
    out.backward()
    assert onp.dtype(x.grad.dtype) == onp.float32


def test_amp_toggle_respected_by_cache():
    x = np.random.uniform(size=(4, 8))
    w = np.random.uniform(size=(8, 4))
    amp.init("bfloat16")
    try:
        assert onp.dtype(np.dot(x, w).dtype) == onp.dtype(ml_dtypes.bfloat16)
    finally:
        amp.deinit()
    assert onp.dtype(np.dot(x, w).dtype) == onp.float32


def test_convert_hybrid_block_selective_cast():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.BatchNorm(),
            gluon.nn.Dense(4))
    net.initialize()
    x = np.random.uniform(size=(2, 8))
    y_ref = net(x).asnumpy()
    wrapped = amp.convert_hybrid_block(net, "bfloat16")
    y_amp = wrapped(x)
    assert onp.dtype(y_amp.dtype) == onp.float32
    rel = onp.abs(y_amp.asnumpy() - y_ref).max() / (onp.abs(y_ref).max())
    assert rel < 0.05
    params = net.collect_params()
    assert onp.dtype(params["0.weight"].data().dtype) == \
        onp.dtype(ml_dtypes.bfloat16)
    assert onp.dtype(params["1.gamma"].data().dtype) == onp.float32


# -- gradient compression -----------------------------------------------------

def test_2bit_quantization_values():
    gc = GradientCompression("2bit", threshold=0.5)
    g = np.array(onp.array([0.9, -0.9, 0.2, -0.2, 0.5], "float32"))
    q = gc.compress("k", g)
    onp.testing.assert_array_equal(q.asnumpy(), [0.5, -0.5, 0, 0, 0.5])


def test_2bit_error_feedback_accumulates():
    gc = GradientCompression("2bit", threshold=0.5)
    g = np.array(onp.full((4,), 0.2, "float32"))
    total = onp.zeros(4)
    for _ in range(5):
        total += gc.compress("k", g).asnumpy()
    # 5 × 0.2 = 1.0 of mass; quantized releases 0.5 every ~3rd step —
    # after 5 steps exactly 1.0 has been emitted (error feedback lossless
    # in the long run)
    onp.testing.assert_allclose(total, onp.full((4,), 1.0), atol=1e-6)


def test_fp16_compression_roundtrip():
    gc = GradientCompression("fp16")
    g = np.array(onp.array([1.0, 0.333333, -2.5], "float32"))
    q = gc.compress("k", g)
    onp.testing.assert_allclose(
        q.asnumpy(), g.asnumpy().astype("float16").astype("float32"))


def test_create_validates():
    with pytest.raises(ValueError):
        create({"threshold": 0.5})
    with pytest.raises(ValueError):
        GradientCompression("1bit")
    with pytest.raises(ValueError):
        GradientCompression("2bit", threshold=0)


def test_trainer_with_compression_converges():
    # error feedback makes compressed SGD converge on linear regression
    rng = onp.random.RandomState(0)
    X = np.array(rng.uniform(size=(128, 4)).astype("float32"))
    W = np.array(rng.uniform(size=(4, 1)).astype("float32"))
    Y = X @ W
    net = gluon.nn.Dense(1, use_bias=False)
    net.initialize()
    # compression sees RAW pushed grads (pre-rescale, like the reference's
    # ZPush payloads) — threshold must match that scale (grads ~1e2 here)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.3},
                            compression_params={"type": "2bit",
                                                "threshold": 2.0})
    loss_fn = gluon.loss.L2Loss()
    first = last = None
    for i in range(400):
        with autograd.record():
            loss = loss_fn(net(X), Y)
        loss.backward()
        trainer.step(128)
        v = float(loss.mean().item())
        first = v if first is None else first
        last = v
    # quantization noise sets the loss floor; 5× reduction demonstrates
    # the error-feedback loop is working (without it the loss stalls flat)
    assert last < 0.2 * first, (first, last)


def test_sparse_grads_not_compressed():
    from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray

    emb = gluon.nn.Embedding(50, 4, sparse_grad=True)
    emb.initialize()
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            compression_params={"type": "2bit",
                                                "threshold": 0.5})
    with autograd.record():
        emb(np.array(onp.array([1, 2], "int32"))).sum().backward()
    trainer.step(1)  # must not crash / densify
    assert isinstance(emb.weight.data()._grad, RowSparseNDArray)
