"""callback / engine / dlpack / registry / libinfo parity-module tests."""
import logging
import types

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def test_speedometer_logs(caplog):
    sp = mx.callback.Speedometer(batch_size=32, frequent=2, auto_reset=False)
    from incubator_mxnet_tpu import gluon

    m = gluon.metric.Accuracy()
    m.update(mnp.array([1]), mnp.array([[0.1, 0.9]]))
    with caplog.at_level(logging.INFO):
        for nbatch in range(1, 5):
            sp(types.SimpleNamespace(epoch=0, nbatch=nbatch, eval_metric=m))
    assert any("samples/sec" in r.message for r in caplog.records)


def test_do_checkpoint_callback(tmp_path):
    from incubator_mxnet_tpu import sym

    a = sym.Variable("a")
    net = a * 2.0
    cb = mx.callback.do_checkpoint(str(tmp_path / "m"), period=2)
    cb(1, net, {"a": NDArray(onp.ones(2, onp.float32))}, {})
    s2, arg, _ = mx.model.load_checkpoint(str(tmp_path / "m"), 2)
    assert s2.list_arguments() == ["a"]
    onp.testing.assert_array_equal(arg["a"].asnumpy(), onp.ones(2))


def test_engine_bulk_scope():
    prev = mx.engine.set_bulk_size(7)
    assert mx.engine.set_bulk_size(prev) == 7
    with mx.engine.bulk(32):
        x = mnp.ones((4,)) + 1.0  # ops run normally inside the scope
    onp.testing.assert_array_equal(x.asnumpy(), onp.full(4, 2.0))


def test_context_module_alias():
    assert mx.context.Context is mx.Context
    assert mx.context.cpu().device_type in ("cpu",)
    assert mx.context.current_context() is not None


def test_executor_module_alias():
    from incubator_mxnet_tpu.symbol.executor import Executor

    assert mx.executor.Executor is Executor


def test_dlpack_roundtrip():
    x = NDArray(onp.arange(6, dtype=onp.float32).reshape(2, 3))
    # the reference pattern: from_dlpack(to_dlpack_for_read(x))
    y = mx.dlpack.from_dlpack(mx.dlpack.to_dlpack_for_read(x))
    onp.testing.assert_array_equal(y.asnumpy(), x.asnumpy())
    # numpy can consume the export too
    z = onp.from_dlpack(mx.dlpack.to_dlpack_for_write(x))
    onp.testing.assert_array_equal(z, x.asnumpy())
    with pytest.raises(TypeError, match="PyCapsule"):
        mx.dlpack.from_dlpack(object())


def test_dlpack_torch_interop():
    torch = pytest.importorskip("torch")
    t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    nd = mx.dlpack.from_dlpack(t)
    onp.testing.assert_array_equal(nd.asnumpy(), t.numpy())


def test_registry_register_create():
    class Base:
        pass

    register = mx.registry.get_register_func(Base, "thing")
    create = mx.registry.get_create_func(Base, "thing")
    alias = mx.registry.get_alias_func(Base, "thing")

    @register
    class Foo(Base):
        def __init__(self, v=1):
            self.v = v

    @alias("second")
    class Bar(Base):
        pass

    assert isinstance(create("foo", v=3), Foo)
    assert create("foo", v=3).v == 3
    assert isinstance(create("second"), Bar)
    inst = Foo()
    assert create(inst) is inst
    assert isinstance(create('["foo", {"v": 9}]'), Foo)
    with pytest.raises(ValueError, match="not registered"):
        create("nope")
    with pytest.raises(TypeError):
        register(int)


def test_resize_keep_ratio_shorter_edge():
    from incubator_mxnet_tpu import gluon

    t = gluon.data.vision.transforms.Resize(8, keep_ratio=True)
    x = NDArray(onp.zeros((6, 12, 3), onp.float32))  # H=6 < W=12
    out = t(x)
    assert out.shape == (8, 16, 3)  # shorter edge → 8, aspect preserved


def test_multibox_mining_threshold_band():
    from incubator_mxnet_tpu import numpy_extension as npx

    x = mnp.zeros((1, 1, 2, 1))
    anchors = npx.multibox_prior(x, sizes=[0.5])
    # anchor 0 = [0,0,1,0.5], anchor 1 = [0,0.5,1,1]; gt [0,0,1,0.6]:
    # IoU(a0)≈0.83 (forced positive), IoU(a1)≈0.10
    label = mnp.array(onp.array([[[0.0, 0.0, 0.0, 1.0, 0.6]]], onp.float32))
    pred = onp.zeros((1, 2, 2), onp.float32)
    pred[0, 1, 1] = 0.99  # anchor 1 is a confident candidate

    def run(thresh):
        _, _, cls_t = npx.multibox_target(
            anchors, label, mnp.array(pred), overlap_threshold=0.9,
            negative_mining_ratio=3.0, negative_mining_thresh=thresh)
        return cls_t.asnumpy()[0]

    # thresh above anchor1's IoU → it's a mining candidate → background
    c = run(0.5)
    assert c[0] == 1.0 and c[1] == 0.0
    # thresh below anchor1's IoU → in-between band → ignored
    c = run(0.05)
    assert c[0] == 1.0 and c[1] == -1.0


def test_libinfo():
    assert mx.libinfo.__version__.startswith("2.0")
    libs = mx.libinfo.find_lib_path()
    assert all(p.endswith(".so") for p in libs)
    assert mx.libinfo.find_include_path().endswith("ext")


def test_env_knob_registry_and_bulk(monkeypatch):
    table = mx.util.env_knobs()
    assert "MXNET_ENGINE_BULK_SIZE" in table
    monkeypatch.setenv("MXNET_ENGINE_BULK_SIZE", "42")
    mx.util._apply_env_config()
    assert mx.engine.set_bulk_size(15) == 42  # was applied


def test_env_num_workers(monkeypatch):
    monkeypatch.setenv("MXNET_CPU_WORKER_NTHREADS", "3")
    assert mx.util.default_num_workers() == 3
    monkeypatch.setenv("MXNET_CPU_WORKER_NTHREADS", "bogus")
    assert mx.util.default_num_workers() == 0
