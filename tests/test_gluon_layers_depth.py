"""Per-layer gluon depth: output shapes/values against hand math, train
vs eval behavior, parameter shapes after deferred init, grads flow
(reference: `tests/python/unittest/test_gluon.py` per-layer blocks)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np
from incubator_mxnet_tpu.gluon import nn

RNG = onp.random.RandomState(23)


def _x(*shape):
    return np.array(RNG.uniform(-1, 1, shape).astype("float32"))


def _init(layer, x):
    layer.initialize()
    out = layer(x)
    return out


# -- Dense -------------------------------------------------------------------

def test_dense_shapes_flatten_true():
    l = nn.Dense(7)
    out = _init(l, _x(4, 3, 5))
    assert out.shape == (4, 7)
    assert l.weight.shape == (7, 15)


def test_dense_shapes_flatten_false():
    l = nn.Dense(7, flatten=False)
    out = _init(l, _x(4, 3, 5))
    assert out.shape == (4, 3, 7)
    assert l.weight.shape == (7, 5)


def test_dense_no_bias():
    l = nn.Dense(3, use_bias=False, in_units=4)
    l.initialize()
    assert l.bias is None
    x = _x(2, 4)
    ref = x.asnumpy() @ l.weight.data().asnumpy().T
    onp.testing.assert_allclose(l(x).asnumpy(), ref, rtol=1e-5)


def test_dense_activation_applied():
    l = nn.Dense(5, activation="relu", in_units=4)
    l.initialize()
    out = l(_x(8, 4)).asnumpy()
    assert (out >= 0).all()


def test_dense_grad_flows():
    l = nn.Dense(3, in_units=4)
    l.initialize()
    x = _x(2, 4)
    with autograd.record():
        y = l(x).sum()
    y.backward()
    assert l.weight.data()._grad is not None


# -- Conv / Pool -------------------------------------------------------------

def test_conv2d_shape_same_pad():
    l = nn.Conv2D(8, 3, padding=1, in_channels=3)
    out = _init(l, _x(2, 3, 16, 16))
    assert out.shape == (2, 8, 16, 16)


def test_conv2d_stride_shape():
    l = nn.Conv2D(4, 3, strides=2, in_channels=3)
    out = _init(l, _x(2, 3, 17, 17))
    assert out.shape == (2, 4, 8, 8)


def test_conv2d_dilation_shape():
    l = nn.Conv2D(4, 3, dilation=2, in_channels=3)
    out = _init(l, _x(2, 3, 16, 16))
    assert out.shape == (2, 4, 12, 12)


def test_conv2d_groups():
    l = nn.Conv2D(8, 3, padding=1, groups=2, in_channels=4)
    out = _init(l, _x(1, 4, 8, 8))
    assert out.shape == (1, 8, 8, 8)
    assert l.weight.shape == (8, 2, 3, 3)


def test_conv1d_shape():
    l = nn.Conv1D(6, 3, in_channels=2)
    out = _init(l, _x(2, 2, 20))
    assert out.shape == (2, 6, 18)


def test_conv3d_shape():
    l = nn.Conv3D(4, 2, in_channels=1)
    out = _init(l, _x(1, 1, 6, 6, 6))
    assert out.shape == (1, 4, 5, 5, 5)


def test_conv2d_transpose_shape():
    l = nn.Conv2DTranspose(3, 3, strides=2, in_channels=4)
    out = _init(l, _x(1, 4, 8, 8))
    assert out.shape[1] == 3 and out.shape[2] > 8


def test_maxpool_value():
    l = nn.MaxPool2D(2)
    x = np.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    out = l(x).asnumpy()
    onp.testing.assert_array_equal(out[0, 0], [[5, 7], [13, 15]])


def test_avgpool_value():
    l = nn.AvgPool2D(2)
    x = np.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    out = l(x).asnumpy()
    onp.testing.assert_allclose(out[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_global_pools():
    x = _x(2, 3, 5, 5)
    g1 = nn.GlobalAvgPool2D()(x)
    g2 = nn.GlobalMaxPool2D()(x)
    assert g1.shape == (2, 3, 1, 1)
    onp.testing.assert_allclose(g1.asnumpy()[..., 0, 0],
                                x.asnumpy().mean(axis=(2, 3)), rtol=1e-5)
    onp.testing.assert_allclose(g2.asnumpy()[..., 0, 0],
                                x.asnumpy().max(axis=(2, 3)), rtol=1e-5)


# -- Norms -------------------------------------------------------------------

def test_batchnorm_train_normalizes():
    l = nn.BatchNorm(in_channels=4)
    l.initialize()
    x = _x(64, 4, 3, 3)
    with autograd.record():
        out = l(x)
    o = out.asnumpy()
    onp.testing.assert_allclose(o.mean(axis=(0, 2, 3)), 0.0, atol=0.05)
    onp.testing.assert_allclose(o.var(axis=(0, 2, 3)), 1.0, atol=0.1)


def test_batchnorm_eval_uses_running_stats():
    l = nn.BatchNorm(in_channels=2)
    l.initialize()
    x = _x(8, 2, 2, 2)
    for _ in range(10):
        with autograd.record():
            l(x)
    out_eval = l(x).asnumpy()      # eval mode: running stats
    assert onp.isfinite(out_eval).all()
    rm = l.running_mean.data().asnumpy()
    assert not onp.allclose(rm, 0.0)    # stats actually updated


def test_layernorm_normalizes_last_axis():
    l = nn.LayerNorm(in_channels=6)
    l.initialize()
    x = _x(4, 6)
    o = l(x).asnumpy()
    onp.testing.assert_allclose(o.mean(axis=-1), 0.0, atol=1e-5)
    onp.testing.assert_allclose(o.var(axis=-1), 1.0, atol=1e-3)


def test_groupnorm_shape():
    l = nn.GroupNorm(num_groups=2, in_channels=4)
    l.initialize()
    out = l(_x(2, 4, 5, 5))
    assert out.shape == (2, 4, 5, 5)


def test_instancenorm_normalizes_spatial():
    l = nn.InstanceNorm(in_channels=3)
    l.initialize()
    x = _x(2, 3, 8, 8)
    o = l(x).asnumpy()
    onp.testing.assert_allclose(o.mean(axis=(2, 3)), 0.0, atol=1e-4)


# -- Activations / Dropout / Embedding ---------------------------------------

def test_activation_kinds():
    x = _x(3, 4)
    for kind, ref in [("relu", lambda v: onp.maximum(v, 0)),
                      ("sigmoid", lambda v: 1 / (1 + onp.exp(-v))),
                      ("tanh", onp.tanh),
                      ("softrelu", lambda v: onp.log1p(onp.exp(v)))]:
        out = nn.Activation(kind)(x).asnumpy()
        onp.testing.assert_allclose(out, ref(x.asnumpy()), rtol=1e-4,
                                    atol=1e-5)


def test_leaky_relu():
    l = nn.LeakyReLU(0.1)
    x = np.array(onp.array([-2.0, 3.0], "float32"))
    onp.testing.assert_allclose(l(x).asnumpy(), [-0.2, 3.0], rtol=1e-6)


def test_prelu_learns_slope():
    l = nn.PReLU()
    l.initialize()
    x = np.array(onp.array([[-1.0, 2.0]], "float32"))
    out = l(x).asnumpy()
    assert out[0, 1] == pytest.approx(2.0)


def test_elu_selu_gelu_swish():
    x = _x(4, 4)
    for layer in (nn.ELU(), nn.SELU(), nn.GELU(), nn.Swish()):
        out = layer(x)
        assert out.shape == x.shape
        assert onp.isfinite(out.asnumpy()).all()


def test_dropout_eval_identity():
    l = nn.Dropout(0.5)
    x = _x(8, 8)
    onp.testing.assert_array_equal(l(x).asnumpy(), x.asnumpy())


def test_dropout_train_zeroes_and_scales():
    mx.random.seed(3)
    l = nn.Dropout(0.5)
    x = np.array(onp.ones((64, 64), "float32"))
    with autograd.record():
        out = l(x)
    o = out.asnumpy()
    zero_frac = (o == 0).mean()
    assert 0.3 < zero_frac < 0.7
    kept = o[o != 0]
    onp.testing.assert_allclose(kept, 2.0, rtol=1e-5)


def test_embedding_lookup_rows():
    l = nn.Embedding(10, 4)
    l.initialize()
    idx = np.array(onp.array([1, 7, 1], "float32"))
    out = l(idx).asnumpy()
    w = l.weight.data().asnumpy()
    onp.testing.assert_array_equal(out, w[[1, 7, 1]])


def test_flatten_layer():
    out = nn.Flatten()(_x(2, 3, 4, 5))
    assert out.shape == (2, 60)


def test_identity_layer():
    x = _x(3, 3)
    onp.testing.assert_array_equal(nn.Identity()(x).asnumpy(), x.asnumpy())


# -- containers --------------------------------------------------------------

def test_hybridsequential_composes():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    out = net(_x(4, 6))
    assert out.shape == (4, 2)
    assert len(net) == 2


def test_sequential_getitem_slice():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4), nn.Dense(2))
    net.initialize()
    sub = net[1:]
    assert len(sub) == 2


def test_collect_params_prefix_regex():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    allp = net.collect_params()
    assert len(allp) == 4
    w_only = net.collect_params(".*weight")
    assert len(w_only) == 2


def test_named_children_and_repr():
    net = nn.HybridSequential()
    net.add(nn.Dense(3))
    assert "Dense" in repr(net)


# -- parameter mechanics -----------------------------------------------------

def test_deferred_init_resolves_on_first_call():
    l = nn.Dense(5)
    l.initialize()
    assert l.weight.shape[1] == 0         # unknown until data flows
    l(_x(2, 7))
    assert l.weight.shape == (5, 7)


def test_uninitialized_use_raises():
    l = nn.Dense(5, in_units=3)
    from incubator_mxnet_tpu.gluon.parameter import DeferredInitializationError

    del DeferredInitializationError
    with pytest.raises(Exception):
        l(_x(2, 3))                        # not initialized


def test_setattr_replaces_child():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4))
    net.initialize()
    ref = net(_x(2, 4))
    net._children["0"] = nn.Identity()
    out = net(_x(2, 4))
    assert out.shape == (2, 4)
    del ref


def test_share_parameters_between_blocks():
    a = nn.Dense(4, in_units=6)
    a.initialize()
    b = nn.Dense(4, in_units=6)
    b.share_parameters(a.collect_params())
    x = _x(3, 6)
    onp.testing.assert_array_equal(a(x).asnumpy(), b(x).asnumpy())


def test_save_load_parameters_roundtrip(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    x = _x(2, 4)
    ref = net(x).asnumpy()
    p = str(tmp_path / "m.params")
    net.save_parameters(p)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net2.load_parameters(p)
    onp.testing.assert_array_equal(net2(x).asnumpy(), ref)


def test_zero_grad_clears():
    l = nn.Dense(3, in_units=4)
    l.initialize()
    x = _x(2, 4)
    with autograd.record():
        l(x).sum().backward()
    l.collect_params().zero_grad()
    g = l.weight.data()._grad
    assert g is None or not g.asnumpy().any()


def test_grad_req_null_skips_grad():
    l = nn.Dense(3, in_units=4)
    l.initialize()
    l.weight.grad_req = "null"
    x = _x(2, 4)
    with autograd.record():
        y = l(x).sum()
    y.backward()
    assert l.weight.data()._grad is None


def test_cast_block_dtype():
    l = nn.Dense(4, in_units=4)
    l.initialize()
    l.cast("float16")
    assert "float16" in str(l.weight.data().dtype)

def test_maxpool_ceil_mode_full_convention():
    """pooling_convention='full' (ceil_mode): partial final windows emit
    (reference PoolingParam, src/operator/nn/pooling-inl.h)."""
    import numpy as onp

    from incubator_mxnet_tpu import np
    from incubator_mxnet_tpu.gluon import nn

    x = np.array(onp.arange(50).reshape(1, 2, 5, 5).astype("float32"))
    assert nn.MaxPool2D(2, 2, ceil_mode=False)(x).shape == (1, 2, 2, 2)
    out = nn.MaxPool2D(2, 2, ceil_mode=True)(x)
    assert out.shape == (1, 2, 3, 3)
    assert float(out.asnumpy()[0, 0, 2, 2]) == 24.0  # partial 1x1 window


def test_avgpool_ceil_mode_clipped_divisor():
    """Ceil-mode avg pool divides partial windows by their CLIPPED size
    (reference pool.h: hend = min(hstart+k, height+pad)), not the full
    kernel area."""
    import numpy as onp

    from incubator_mxnet_tpu import np
    from incubator_mxnet_tpu.gluon import nn

    x = np.array(onp.arange(25).reshape(1, 1, 5, 5).astype("float32"))
    out = nn.AvgPool2D(2, 2, ceil_mode=True)(x)
    assert out.shape == (1, 1, 3, 3)
    # bottom-right ceil window covers only element [4,4]=24 -> avg = 24
    assert float(out.asnumpy()[0, 0, 2, 2]) == 24.0
    # bottom edge window covers [4,2],[4,3] -> (22+23)/2
    assert float(out.asnumpy()[0, 0, 2, 1]) == 22.5
