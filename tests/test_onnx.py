"""ONNX export tests (reference: tests under
`tests/python-pytest/onnx/test_onnxruntime*.py` validate mx2onnx exports by
running them in onnxruntime; here the exported protobuf is executed by the
package's own numpy ONNX runtime and validated structurally with protoc)."""
import os
import shutil
import subprocess

import numpy as onp
import pytest

import incubator_mxnet_tpu.onnx as mxonnx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu import gluon


def _roundtrip(net, x, tol=1e-4, **kw):
    y = net(x)
    import tempfile

    d = tempfile.mkdtemp()
    f = os.path.join(d, "m.onnx")
    mxonnx.export_model(net, f, inputs=[x], **kw)
    outs = mxonnx.runtime.run_model(f, {"data": x.asnumpy()})
    onp.testing.assert_allclose(y.asnumpy(), outs[0], rtol=tol, atol=tol)
    return f


def test_mlp_batchnorm_export():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.BatchNorm(),
            gluon.nn.Dense(4))
    net.initialize()
    _roundtrip(net, np.random.uniform(size=(3, 8)))


def test_convnet_export():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(4, 3, padding=1, strides=2),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(3))
    net.initialize()
    _roundtrip(net, np.random.uniform(size=(2, 3, 16, 16)))


def test_dynamic_batch_export_runs_other_batch_sizes():
    # Flatten bakes the batch into a reshape unless exported symbolically —
    # exactly the case dynamic_batch must handle.
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2), gluon.nn.Flatten(), gluon.nn.Dense(3))
    net.initialize()
    x2 = np.random.uniform(size=(2, 3, 8, 8))
    import tempfile

    f = os.path.join(tempfile.mkdtemp(), "m.onnx")
    mxonnx.export_model(net, f, inputs=[x2], dynamic_batch=True)
    x5 = np.random.uniform(size=(5, 3, 8, 8))
    outs = mxonnx.runtime.run_model(f, {"data": x5.asnumpy()})
    onp.testing.assert_allclose(net(x5).asnumpy(), outs[0],
                                rtol=1e-4, atol=1e-5)


def test_resnet18_export_and_protoc_validation():
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet18_v1

    net = resnet18_v1()
    net.initialize()
    x = np.random.uniform(size=(1, 3, 32, 32))
    f = _roundtrip(net, x, tol=1e-3, dynamic_batch=True)

    meta = mxonnx.get_model_metadata(f)
    assert meta["input_tensor_data"][0][0] == "data"
    assert meta["input_tensor_data"][0][1][0] == "batch"  # dynamic batch dim

    # exported at batch 1, must run at batch 2
    x2 = np.random.uniform(size=(2, 3, 32, 32))
    outs = mxonnx.runtime.run_model(f, {"data": x2.asnumpy()})
    onp.testing.assert_allclose(net(x2).asnumpy(), outs[0],
                                rtol=1e-3, atol=1e-4)

    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    proto_dir = os.path.dirname(mxonnx.proto.__file__)
    with open(f, "rb") as fh:
        r = subprocess.run(
            ["protoc", f"--proto_path={proto_dir}",
             "--decode=onnx.ModelProto", "onnx_subset.proto"],
            stdin=fh, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert 'op_type: "Conv"' in r.stdout
    assert 'op_type: "MaxPool"' in r.stdout
    assert 'op_type: "Gemm"' in r.stdout


def test_activations_export():
    for act in ["sigmoid", "tanh", "softrelu", "relu"]:
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(6), gluon.nn.Activation(act), gluon.nn.Dense(2))
        net.initialize()
        _roundtrip(net, np.random.uniform(low=-1, size=(2, 4)))


def test_embedding_softmax_export():
    from incubator_mxnet_tpu.gluon.block import HybridBlock

    class Net2(HybridBlock):
        def __init__(self):
            super().__init__()
            self.emb = gluon.nn.Embedding(20, 8)
            self.dense = gluon.nn.Dense(5)

        def forward(self, x):
            from incubator_mxnet_tpu import npx

            h = self.emb(x)
            return npx.softmax(self.dense(h.reshape((h.shape[0], -1))))

    net = Net2()
    net.initialize()
    x = np.random.randint(0, 20, (3, 4))
    y = net(x)
    import tempfile

    f = os.path.join(tempfile.mkdtemp(), "m.onnx")
    mxonnx.export_model(net, f, inputs=[x])
    outs = mxonnx.runtime.run_model(f, {"data": x.asnumpy()})
    onp.testing.assert_allclose(y.asnumpy(), outs[0], rtol=1e-4, atol=1e-5)


def test_unsupported_op_raises():
    from incubator_mxnet_tpu.gluon.block import HybridBlock

    class Weird(HybridBlock):
        def forward(self, x):
            from incubator_mxnet_tpu import np as mnp

            return mnp.sort(x, axis=-1)

    net = Weird()
    x = np.random.uniform(size=(2, 5))
    import tempfile

    f = os.path.join(tempfile.mkdtemp(), "m.onnx")
    with pytest.raises((mxonnx.UnsupportedOp, NotImplementedError)):
        mxonnx.export_model(net, f, inputs=[x])
