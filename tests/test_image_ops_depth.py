"""Image op depth: npx.image resize/crop/normalize/flip semantics plus
the imperative mx.image augmenter helpers (reference:
`src/operator/image/image_random-inl.h`, `python/mxnet/image/`)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import image as mximage
from incubator_mxnet_tpu import np, npx

RNG = onp.random.RandomState(41)


def _img(h=8, w=10, c=3):
    return RNG.randint(0, 255, (h, w, c)).astype(onp.uint8)


def test_to_tensor_scales_and_transposes():
    im = _img()
    got = npx.image.to_tensor(np.array(im)).asnumpy()
    assert got.shape == (3, 8, 10)
    onp.testing.assert_allclose(got, im.transpose(2, 0, 1) / 255.0,
                                rtol=1e-6)


def test_normalize_channelwise():
    x = np.array(onp.ones((3, 4, 4), "float32"))
    got = npx.image.normalize(x, mean=(0.5, 0.0, 1.0),
                              std=(0.5, 1.0, 2.0)).asnumpy()
    onp.testing.assert_allclose(got[0], 1.0, rtol=1e-5)
    onp.testing.assert_allclose(got[1], 1.0, rtol=1e-5)
    onp.testing.assert_allclose(got[2], 0.0, atol=1e-6)


def test_resize_shape_and_dtype():
    im = _img(8, 10)
    got = npx.image.resize(np.array(im), size=(20, 16))  # (w, h)
    assert got.shape == (16, 20, 3)


def test_resize_identity_when_same_size():
    im = _img(8, 8)
    got = npx.image.resize(np.array(im), size=(8, 8)).asnumpy()
    onp.testing.assert_allclose(got.astype("float32"),
                                im.astype("float32"), atol=1.0)


def test_crop_exact_region():
    im = _img(10, 12)
    got = npx.image.crop(np.array(im), 2, 3, 5, 4).asnumpy()  # x,y,w,h
    onp.testing.assert_array_equal(got, im[3:7, 2:7])


def test_fixed_crop_matches_slice():
    im = _img(10, 12)
    got = mximage.fixed_crop(np.array(im), 1, 2, 6, 5).asnumpy()
    onp.testing.assert_array_equal(got, im[2:7, 1:7])


def test_flip_left_right():
    im = _img()
    got = npx.image.flip_left_right(np.array(im)).asnumpy()
    onp.testing.assert_array_equal(got, im[:, ::-1])


def test_flip_top_bottom():
    im = _img()
    got = npx.image.flip_top_bottom(np.array(im)).asnumpy()
    onp.testing.assert_array_equal(got, im[::-1])


def test_resize_short_keeps_aspect():
    im = _img(8, 16)
    out = mximage.resize_short(np.array(im), 4)
    assert out.shape == (4, 8, 3)


def test_center_crop_shape():
    im = _img(10, 12)
    out, (x0, y0, w, h) = mximage.center_crop(np.array(im), (6, 4))
    assert out.shape == (4, 6, 3)
    assert (x0, y0, w, h) == (3, 3, 6, 4)


def test_random_crop_within_bounds():
    mx.random.seed(3)
    im = _img(10, 12)
    out, (x0, y0, w, h) = mximage.random_crop(np.array(im), (5, 5))
    assert out.shape == (5, 5, 3)
    assert 0 <= x0 <= 7 and 0 <= y0 <= 5


def test_color_normalize_helper():
    im = onp.full((4, 4, 3), 128, "uint8")
    out = mximage.color_normalize(
        np.array(im).astype("float32") / 255.0,
        np.array(onp.array([0.5, 0.5, 0.5], "float32")),
        np.array(onp.array([0.5, 0.5, 0.5], "float32"))).asnumpy()
    onp.testing.assert_allclose(out, (128 / 255 - 0.5) / 0.5, rtol=1e-4)


def test_imdecode_imencode_roundtrip():
    cv2 = pytest.importorskip("cv2")
    im = _img(16, 16)
    ok, buf = cv2.imencode(".png", im)     # png = lossless
    assert ok
    got = mximage.imdecode(buf.tobytes()).asnumpy()
    onp.testing.assert_array_equal(got, im[:, :, ::-1])  # BGR→RGB parity


def test_hue_brightness_augmenters_change_image():
    mx.random.seed(4)
    im = np.array(_img().astype("float32"))
    aug = mximage.BrightnessJitterAug(0.5)
    out = aug(im).asnumpy()
    assert out.shape == im.shape
    assert not onp.allclose(out, im.asnumpy())


def test_horizontal_flip_aug_deterministic_p1():
    aug = mximage.HorizontalFlipAug(1.0)
    im = np.array(_img().astype("float32"))
    out = aug(im).asnumpy()
    onp.testing.assert_array_equal(out, im.asnumpy()[:, ::-1])


def test_cast_aug():
    aug = mximage.CastAug()
    im = np.array(_img())
    assert "float32" in str(aug(im).dtype)


def test_resize_aug_sequence():
    aug = mximage.ResizeAug(6)
    im = np.array(_img(8, 12).astype("float32"))
    out = aug(im)
    assert min(out.shape[:2]) == 6


def test_augmenter_list_compose():
    augs = mximage.CreateAugmenter((3, 6, 6), resize=8, rand_mirror=True)
    assert len(augs) >= 2
    im = np.array(_img(10, 10).astype("float32"))
    out = im
    for a in augs:
        out = a(out)
    assert out.shape[-1] == 3 or out.shape[0] == 3


def test_gluon_transforms_pipeline():
    from incubator_mxnet_tpu.gluon.data.vision import transforms

    tf = transforms.Compose([transforms.Resize(6),
                             transforms.CenterCrop(4),
                             transforms.ToTensor(),
                             transforms.Normalize(0.5, 0.5)])
    out = tf(np.array(_img(10, 12)))
    assert out.shape == (3, 4, 4)
    assert float(out.asnumpy().max()) <= 1.0


def test_random_resized_crop_transform():
    from incubator_mxnet_tpu.gluon.data.vision import transforms

    mx.random.seed(5)
    tf = transforms.RandomResizedCrop(6)
    out = tf(np.array(_img(12, 12)))
    assert out.shape[:2] == (6, 6)