"""Decoder-only causal LM (`models/gpt.py`): causality, training step,
hybridize, generation (reference role: GluonNLP GPT-2)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np, optimizer
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.parallel.sharded import DataParallel


@pytest.fixture(scope="module")
def net():
    mx.random.seed(0)
    m = gpt_tiny(vocab_size=97, max_length=32, dropout=0.0)
    m.initialize()
    return m


def _tok(batch, t, seed=0, vocab=97):
    r = onp.random.RandomState(seed)
    return np.array(r.randint(0, vocab, (batch, t)).astype("int32"))


def test_forward_shape_and_determinism(net):
    x = _tok(2, 16)
    out = net(x)
    assert out.shape == (2, 16, 97)
    onp.testing.assert_allclose(out.asnumpy(), net(x).asnumpy(), rtol=1e-6)


def test_causality(net):
    """Changing a future token must not change past logits."""
    x1 = _tok(1, 16, seed=1)
    x2_np = x1.asnumpy().copy()
    x2_np[0, 10:] = (x2_np[0, 10:] + 1) % 97     # perturb tokens >= 10
    out1 = net(x1).asnumpy()
    out2 = net(np.array(x2_np.astype("int32"))).asnumpy()
    onp.testing.assert_allclose(out1[0, :10], out2[0, :10],
                                rtol=1e-5, atol=1e-5)
    assert not onp.allclose(out1[0, 10:], out2[0, 10:])


def test_train_step_reduces_loss(net):
    """Next-token LM training on a repeating pattern: loss must drop."""
    mx.random.seed(3)
    m = gpt_tiny(vocab_size=17, max_length=32, dropout=0.0)
    m.initialize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def lm_loss(logits, y):
        return ce(logits.reshape(-1, 17), y.reshape(-1))

    dp = DataParallel(m, lm_loss, optimizer.Adam(learning_rate=3e-3))
    seq = onp.tile(onp.arange(16), 3)[:32].astype("int32")  # periodic
    x = np.array(onp.stack([seq[:-1]] * 4))
    y = np.array(onp.stack([seq[1:]] * 4))
    first = float(dp.step(x, y).asnumpy())
    for _ in range(30):
        last = float(dp.step(x, y).asnumpy())
    assert last < first * 0.5, (first, last)


def test_hybridize_matches_eager(net):
    x = _tok(2, 12, seed=5)
    ref = net(x).asnumpy()
    net.hybridize()
    out1 = net(x).asnumpy()   # eager probe
    out2 = net(x).asnumpy()   # compiled
    onp.testing.assert_allclose(out1, ref, rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(out2, ref, rtol=1e-4, atol=1e-5)
    net.hybridize(False)


def test_generate_greedy_extends(net):
    x = _tok(2, 4, seed=7)
    out = net.generate(x, max_new_tokens=5)
    assert out.shape == (2, 9)
    onp.testing.assert_array_equal(out.asnumpy()[:, :4], x.asnumpy())
    # greedy decode is deterministic
    out2 = net.generate(x, max_new_tokens=5)
    onp.testing.assert_array_equal(out.asnumpy(), out2.asnumpy())
    # top-k restricted sampling stays in vocab
    out3 = net.generate(x, max_new_tokens=3, top_k=5)
    assert int(out3.asnumpy().max()) < 97
