"""Decoder-only causal LM (`models/gpt.py`): causality, training step,
hybridize, generation (reference role: GluonNLP GPT-2)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np, optimizer
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.parallel.sharded import DataParallel


@pytest.fixture(scope="module")
def net():
    mx.random.seed(0)
    m = gpt_tiny(vocab_size=97, max_length=32, dropout=0.0)
    m.initialize()
    return m


def _tok(batch, t, seed=0, vocab=97):
    r = onp.random.RandomState(seed)
    return np.array(r.randint(0, vocab, (batch, t)).astype("int32"))


def test_forward_shape_and_determinism(net):
    x = _tok(2, 16)
    out = net(x)
    assert out.shape == (2, 16, 97)
    onp.testing.assert_allclose(out.asnumpy(), net(x).asnumpy(), rtol=1e-6)


def test_causality(net):
    """Changing a future token must not change past logits."""
    x1 = _tok(1, 16, seed=1)
    x2_np = x1.asnumpy().copy()
    x2_np[0, 10:] = (x2_np[0, 10:] + 1) % 97     # perturb tokens >= 10
    out1 = net(x1).asnumpy()
    out2 = net(np.array(x2_np.astype("int32"))).asnumpy()
    onp.testing.assert_allclose(out1[0, :10], out2[0, :10],
                                rtol=1e-5, atol=1e-5)
    assert not onp.allclose(out1[0, 10:], out2[0, 10:])


def test_train_step_reduces_loss(net):
    """Next-token LM training on a repeating pattern: loss must drop."""
    mx.random.seed(3)
    m = gpt_tiny(vocab_size=17, max_length=32, dropout=0.0)
    m.initialize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def lm_loss(logits, y):
        return ce(logits.reshape(-1, 17), y.reshape(-1))

    dp = DataParallel(m, lm_loss, optimizer.Adam(learning_rate=3e-3))
    seq = onp.tile(onp.arange(16), 3)[:32].astype("int32")  # periodic
    x = np.array(onp.stack([seq[:-1]] * 4))
    y = np.array(onp.stack([seq[1:]] * 4))
    first = float(dp.step(x, y).asnumpy())
    for _ in range(30):
        last = float(dp.step(x, y).asnumpy())
    assert last < first * 0.5, (first, last)


def test_hybridize_matches_eager(net):
    x = _tok(2, 12, seed=5)
    ref = net(x).asnumpy()
    net.hybridize()
    out1 = net(x).asnumpy()   # eager probe
    out2 = net(x).asnumpy()   # compiled
    onp.testing.assert_allclose(out1, ref, rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(out2, ref, rtol=1e-4, atol=1e-5)
    net.hybridize(False)


def test_generate_greedy_extends(net):
    x = _tok(2, 4, seed=7)
    out = net.generate(x, max_new_tokens=5)
    assert out.shape == (2, 9)
    onp.testing.assert_array_equal(out.asnumpy()[:, :4], x.asnumpy())
    # greedy decode is deterministic
    out2 = net.generate(x, max_new_tokens=5)
    onp.testing.assert_array_equal(out.asnumpy(), out2.asnumpy())
    # top-k restricted sampling stays in vocab
    out3 = net.generate(x, max_new_tokens=3, top_k=5, do_sample=True)
    assert int(out3.asnumpy().max()) < 97


@pytest.fixture(scope="module")
def spicy_net():
    """Random-weight net with non-degenerate logits (scaled init breaks
    the argmax collapse of a freshly initialized model, so greedy parity
    actually exercises token-dependent paths)."""
    mx.random.seed(11)
    m = gpt_tiny(vocab_size=97, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(42)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


def test_kv_cache_greedy_matches_full_forward(spicy_net):
    """The compiled KV-cache decode (one XLA program, static cache) must
    emit exactly the tokens of the eager O(T²) full-forward loop."""
    for seed, (b, t0, tnew) in [(0, (2, 12, 20)), (1, (1, 1, 8)),
                                (2, (3, 7, 1))]:
        x = _tok(b, t0, seed=seed)
        ref = spicy_net.generate(x, tnew, use_cache=False).asnumpy()
        got = spicy_net.generate(x, tnew, use_cache=True).asnumpy()
        assert got.shape == (b, t0 + tnew)
        onp.testing.assert_array_equal(ref, got)


def test_kv_cache_sampling_seeded_and_varied(spicy_net):
    x = _tok(2, 8, seed=3)
    a = spicy_net.generate(x, 12, do_sample=True, top_k=8,
                           temperature=0.9, seed=5).asnumpy()
    b = spicy_net.generate(x, 12, do_sample=True, top_k=8,
                           temperature=0.9, seed=5).asnumpy()
    c = spicy_net.generate(x, 12, do_sample=True, top_k=8,
                           temperature=0.9, seed=6).asnumpy()
    onp.testing.assert_array_equal(a, b)         # seeded => reproducible
    assert not (a == c).all()                     # seed changes the draw
    # all sampled tokens inside the vocab
    assert int(a.max()) < 97 and int(a.min()) >= 0
    # temperature~0 sampling collapses to greedy
    g = spicy_net.generate(x, 12, use_cache=True).asnumpy()
    t0 = spicy_net.generate(x, 12, do_sample=True, temperature=1e-6,
                            seed=5).asnumpy()
    onp.testing.assert_array_equal(g, t0)


def test_kv_cache_respects_max_length(spicy_net):
    x = _tok(1, 60, seed=4)
    with pytest.raises(ValueError):
        spicy_net.generate(x, 8, use_cache=True)   # 68 > max_length 64


def test_bucket_prompt_helper():
    """bucket_prompt pads to the smallest fitting bucket, accounts the
    waste, and passes through prompts beyond every bucket."""
    from incubator_mxnet_tpu.models.decoding import bucket_prompt
    from incubator_mxnet_tpu.telemetry import registry

    ctr = registry.counter(
        "mx_decode_bucket_pad_tokens_total",
        "prompt tokens added by pad-to-bucket in the decode/serving "
        "path (padding waste)")
    before = ctr.value
    ids = onp.arange(10, dtype=onp.int32).reshape(2, 5)
    padded, t0 = bucket_prompt(ids, buckets=(8, 16))
    assert padded.shape == (2, 8) and t0 == 5
    onp.testing.assert_array_equal(onp.asarray(padded)[:, :5], ids)
    assert ctr.value == before + 2 * 3      # 2 rows x 3 pad tokens
    # exact-bucket and beyond-every-bucket prompts pass through unpadded
    p8, t8 = bucket_prompt(onp.zeros((1, 8), onp.int32), buckets=(8, 16))
    assert p8.shape == (1, 8) and t8 == 8
    p20, t20 = bucket_prompt(onp.zeros((1, 20), onp.int32), buckets=(8, 16))
    assert p20.shape == (1, 20) and t20 == 20
    # max_len caps the candidate buckets
    p5, _ = bucket_prompt(onp.zeros((1, 5), onp.int32), buckets=(8, 16),
                          max_len=8)
    assert p5.shape == (1, 8)
    with pytest.raises(ValueError):
        bucket_prompt(onp.zeros((5,), onp.int32))


def test_generate_buckets_share_one_program(spicy_net):
    """Ad-hoc prompt lengths inside one bucket must NOT compile one XLA
    program each — the pre-bucketing behavior this satellite kills."""
    from incubator_mxnet_tpu.models.decoding import GPTDecoder

    dec = GPTDecoder(spicy_net)
    for t0 in (3, 7, 11, 18):              # all land in the 32 bucket
        dec.generate(_tok(1, t0, seed=t0), 4)
    size = getattr(dec._generate_fn, "_cache_size", None)
    if size is not None:                   # jax-version-dependent probe
        assert size() == 1, "one bucket must mean one compiled program"


def test_decoder_auto_refresh_without_explicit_refresh(spicy_net, caplog):
    """Forgetting refresh() after a parameter update must no longer
    produce stale logits: the decoder fingerprints the source Block's
    parameter buffers and auto-refreshes (warning once)."""
    import logging

    from incubator_mxnet_tpu.models.decoding import GPTDecoder

    dec = GPTDecoder(spicy_net)
    x = _tok(1, 6, seed=21)
    before = dec.generate(x, 8).asnumpy()
    p = spicy_net.word_embed.weight
    old = p.data().asnumpy()
    try:
        r = onp.random.RandomState(321)
        p.set_data(np.array(r.normal(0, 0.35, p.shape).astype("float32")))
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.models"):
            after = dec.generate(x, 8).asnumpy()   # NO refresh() call
        assert any("auto-refreshing" in m for m in caplog.messages)
        ref = spicy_net.generate(x, 8, use_cache=False).asnumpy()
        onp.testing.assert_array_equal(after, ref)
        assert not (before == after).all()
        # the warning fires once, not per call
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.models"):
            p.set_data(np.array(old))
            dec.generate(x, 8)
        assert not any("auto-refreshing" in m for m in caplog.messages)
    finally:
        p.set_data(np.array(old))


def test_kv_cache_sees_updated_params(spicy_net):
    """generate() after a parameter change must reflect the new weights
    (the decoder re-reads parameters per call)."""
    x = _tok(1, 6, seed=9)
    before = spicy_net.generate(x, 8).asnumpy()
    p = spicy_net.word_embed.weight
    old = p.data().asnumpy()
    try:
        r = onp.random.RandomState(123)
        p.set_data(np.array(r.normal(0, 0.35, p.shape).astype("float32")))
        after = spicy_net.generate(x, 8).asnumpy()
        ref = spicy_net.generate(x, 8, use_cache=False).asnumpy()
        onp.testing.assert_array_equal(after, ref)
        assert not (before == after).all()
    finally:
        p.set_data(np.array(old))
