"""DGL graph-sampling op family (reference `src/operator/contrib/
dgl_graph.cc` — examples from its op docstrings are the oracles here)."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.ndarray.sparse import csr_matrix


def _k5():
    """The 5-vertex complete graph from dgl_graph.cc:753 (edge ids
    1..20)."""
    data = onp.arange(1, 21, dtype=onp.float32)
    indices = onp.array([1, 2, 3, 4, 0, 2, 3, 4, 0, 1, 3, 4,
                         0, 1, 2, 4, 0, 1, 2, 3], onp.int32)
    indptr = onp.array([0, 4, 8, 12, 16, 20], onp.int32)
    return csr_matrix((data, indices, indptr), shape=(5, 5))


def test_edge_id():
    # dgl_graph.cc:1341 example graph
    x = csr_matrix((onp.array([1, 2, 3], "float32"),
                    onp.array([0, 2, 1], "int32"),
                    onp.array([0, 1, 2, 3], "int32")), shape=(3, 3))
    u = np.array(onp.array([0, 0, 1, 1, 2, 2], "int64"))
    v = np.array(onp.array([0, 1, 1, 2, 0, 1], "int64"))
    out = mx.nd.contrib.edge_id(x, u, v)
    onp.testing.assert_array_equal(out.asnumpy(), [1, -1, -1, 2, -1, 3])


def test_getnnz():
    g = _k5()
    assert int(mx.nd.contrib.getnnz(g).asnumpy()[0]) == 20
    onp.testing.assert_array_equal(
        mx.nd.contrib.getnnz(g, axis=1).asnumpy(), [4] * 5)
    onp.testing.assert_array_equal(
        mx.nd.contrib.getnnz(g, axis=0).asnumpy(), [4] * 5)


def test_dgl_adjacency():
    adj = mx.nd.contrib.dgl_adjacency(_k5())
    dense = adj.asnumpy()
    assert dense.sum() == 20
    assert set(onp.unique(dense)) == {0.0, 1.0}


def test_dgl_subgraph_reference_example():
    # dgl_graph.cc:1130 example
    x = csr_matrix((onp.array([1, 2, 3, 4, 5, 6, 7], "float32"),
                    onp.array([0, 3, 0, 2, 1, 1, 2], "int32"),
                    onp.array([0, 2, 4, 5, 7], "int32")), shape=(4, 4))
    v = np.array(onp.array([0, 1, 2], "int64"))
    sub, mapping = mx.nd.contrib.dgl_subgraph(x, v, return_mapping=True)
    onp.testing.assert_array_equal(
        sub.asnumpy(), [[1, 0, 0], [2, 0, 3], [0, 4, 0]])
    onp.testing.assert_array_equal(
        mapping.asnumpy(), [[1, 0, 0], [3, 0, 4], [0, 5, 0]])


def test_neighbor_uniform_sample_structure():
    g = _k5()
    seed = np.array(onp.arange(5, dtype=onp.int64))
    verts, sub, layer = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, seed, num_args=2, num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    vn = verts.asnumpy()
    assert vn.shape == (6,)
    assert vn[-1] == 5                     # all 5 vertices sampled
    onp.testing.assert_array_equal(sorted(vn[:5]), onp.arange(5))
    sn = sub.asnumpy()
    assert sn.shape == (5, 5)
    # each row sampled ≤ num_neighbor edges, values are original edge ids
    assert ((sn > 0).sum(axis=1) <= 2).all()
    assert set(onp.unique(sn)) <= set(range(21))
    onp.testing.assert_array_equal(layer.asnumpy(), onp.zeros(5))


def test_neighbor_non_uniform_sample_prob_output():
    g = _k5()
    prob = np.array(onp.array([0.9, 0.8, 0.2, 0.4, 0.1], "float32"))
    seed = np.array(onp.arange(5, dtype=onp.int64))
    verts, sub, p, layer = \
        mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
            g, prob, seed, num_args=3, num_hops=1, num_neighbor=2,
            max_num_vertices=5)
    onp.testing.assert_allclose(p.asnumpy(),
                                [0.9, 0.8, 0.2, 0.4, 0.1], rtol=1e-6)
    assert sub.asnumpy().shape == (5, 5)
    assert int(verts.asnumpy()[-1]) == 5


def test_graph_compact():
    g = _k5()
    seed = np.array(onp.array([0, 1], "int64"))
    verts, sub, _layer = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, seed, num_args=2, num_hops=1, num_neighbor=2,
        max_num_vertices=6)
    n = int(verts.asnumpy()[-1])
    compact = mx.nd.contrib.dgl_graph_compact(
        sub, verts, graph_sizes=n, return_mapping=False)
    assert compact.shape == (n, n)
    # compacted edges renumbered 1..nnz
    cn = compact.asnumpy()
    nnz = (cn > 0).sum()
    assert set(cn[cn > 0]) == set(range(1, nnz + 1))


def test_multi_seed_arrays():
    g = _k5()
    s1 = np.array(onp.array([0], "int64"))
    s2 = np.array(onp.array([3], "int64"))
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, s1, s2, num_args=3, num_hops=1, num_neighbor=3,
        max_num_vertices=5)
    assert len(out) == 6                   # 2 x (verts, csr, layer)
    v1, v2 = out[0].asnumpy(), out[1].asnumpy()
    assert v1[0] == 0 and v2[0] == 3
