"""Pipeline parallelism (GPipe over `pp`) and expert-parallel MoE (`ep`):
SPMD correctness on the 8-device CPU mesh — the sharded computation must
equal the same math computed unsharded (`parallel/pipeline.py`,
`parallel/moe.py`)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from incubator_mxnet_tpu.parallel.moe import (moe_dispatch_combine,
                                              moe_ffn_apply, top1_gating,
                                              top2_gating)
from incubator_mxnet_tpu.parallel.pipeline import (PipelineParallel,
                                                   pipeline_apply,
                                                   pipeline_stage_params)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the multi-device CPU mesh")


def _mesh(n, name):
    return Mesh(onp.array(jax.devices()[:n]), (name,))


def test_pipeline_matches_sequential():
    S, M, B, D = 4, 6, 2, 8           # stages, microbatches, micro-bs, dim
    rng = onp.random.RandomState(0)
    ws = jnp.asarray(rng.uniform(-0.5, 0.5, (S, D, D)).astype("float32"))
    x = jnp.asarray(rng.uniform(-1, 1, (M, B, D)).astype("float32"))

    def stage_fn(w, act):
        return jnp.tanh(act @ w)

    # sequential reference: every microbatch through all stages in order
    ref = x
    for s in range(S):
        ref = jax.vmap(lambda mb, w=ws[s]: stage_fn(w, mb))(ref)

    mesh = _mesh(S, "pp")
    f = jax.jit(shard_map(
        lambda w, xs: pipeline_apply(stage_fn, w[0], xs,
                                     axis_name="pp")[None],
        mesh=mesh, in_specs=(P("pp"), P()), out_specs=P("pp")))
    out = f(ws, x)
    # out: (S, M, B, D); only the LAST stage's bank is meaningful
    onp.testing.assert_allclose(onp.asarray(out[-1]), onp.asarray(ref),
                                rtol=2e-5, atol=1e-6)
    # earlier stages must NOT hold the final result (real pipelining)
    assert not onp.allclose(onp.asarray(out[0]), onp.asarray(ref))


def test_pipeline_stage_params_stacking():
    layers = [{"w": jnp.ones((3, 3)) * i} for i in range(8)]
    stacked = pipeline_stage_params(layers, 4)
    assert stacked["w"].shape == (4, 2, 3, 3)
    onp.testing.assert_allclose(onp.asarray(stacked["w"][1, 0]),
                                onp.full((3, 3), 2.0))
    with pytest.raises(ValueError):
        pipeline_stage_params(layers[:6], 4)


def test_top1_gating_capacity():
    logits = jnp.asarray(onp.array(
        [[9, 0], [8, 0], [7, 0], [0, 5]], "float32"))
    combine, dispatch, aux = top1_gating(logits, capacity=2)
    # tokens 0,1 fill expert 0's two slots; token 2 dropped; token 3 -> e1
    assert float(dispatch[0, 0, 0]) == 1.0
    assert float(dispatch[1, 0, 1]) == 1.0
    assert float(dispatch[2].sum()) == 0.0          # over capacity
    assert float(dispatch[3, 1, 0]) == 1.0
    assert float(aux) > 0


def test_moe_ep_matches_unsharded():
    G = 4                               # expert-parallel groups
    T, D, H, E = 32, 8, 16, 4           # tokens per device, dims, experts
    rng = onp.random.RandomState(1)
    x = jnp.asarray(rng.uniform(-1, 1, (G * T, D)).astype("float32"))
    gw = jnp.asarray(rng.uniform(-1, 1, (D, E)).astype("float32"))
    w1 = jnp.asarray(rng.uniform(-0.5, 0.5, (E, D, H)).astype("float32"))
    b1 = jnp.zeros((E, H), jnp.float32)
    w2 = jnp.asarray(rng.uniform(-0.5, 0.5, (E, H, D)).astype("float32"))
    b2 = jnp.zeros((E, D), jnp.float32)

    def sharded(x, gw, w1, b1, w2, b2):
        out, aux = moe_dispatch_combine(
            x, x @ gw, moe_ffn_apply(w1, b1, w2, b2),
            capacity_factor=8.0, axis_name="ep")
        return out, aux.reshape(1)   # per-shard aux, stacked over ep

    mesh = _mesh(G, "ep")
    f = jax.jit(shard_map(
        sharded, mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P("ep"))))
    out_sharded, _aux = f(x, gw, w1, b1, w2, b2)

    # unsharded reference: same math per token shard with ALL experts
    # local (capacity per shard must match: T tokens vs E experts)
    outs = []
    for g in range(G):
        xg = x[g * T:(g + 1) * T]
        o, _ = moe_dispatch_combine(
            xg, xg @ gw, moe_ffn_apply(w1, b1, w2, b2),
            capacity_factor=8.0, axis_name=None)
        outs.append(o)
    ref = jnp.concatenate(outs)
    onp.testing.assert_allclose(onp.asarray(out_sharded), onp.asarray(ref),
                                rtol=2e-4, atol=2e-5)


def test_moe_routes_to_correct_expert():
    """Hand-crafted gates: each token's output must come from ITS expert."""
    D, E = 4, 2
    x = jnp.asarray(onp.eye(4, D, dtype="float32"))
    # force tokens 0,1 -> expert 0; tokens 2,3 -> expert 1
    logits = jnp.asarray(onp.array([[9., 0.], [9., 0.],
                                    [0., 9.], [0., 9.]], "float32"))

    def expert_fn(slots):                       # (E, C, D)
        # expert 0 doubles, expert 1 negates: distinguishable
        return jnp.stack([slots[0] * 2.0, -slots[1]])

    out, _ = moe_dispatch_combine(x, logits, expert_fn,
                                  capacity_factor=2.0, axis_name=None)
    g = float(jax.nn.softmax(logits[0])[0])
    onp.testing.assert_allclose(onp.asarray(out[0]),
                                onp.asarray(x[0] * 2.0 * g), rtol=1e-5)
    onp.testing.assert_allclose(onp.asarray(out[2]),
                                onp.asarray(-x[2] * g), rtol=1e-5)


# ---------------------------------------------------------------------------
# training (round 4: VERDICT #5 — PP/EP must TRAIN, not just forward)
# ---------------------------------------------------------------------------

def test_pipeline_parallel_trains():
    """PipelineParallel: fwd+bwd+SGD through the GPipe schedule — loss
    decreases and the learned params match a single-device reference run
    doing the same math."""
    from incubator_mxnet_tpu import optimizer

    S, M, B, D = 4, 4, 2, 6
    rng = onp.random.RandomState(1)
    ws0 = jnp.asarray(rng.uniform(-0.5, 0.5, (S, D, D)).astype("float32"))
    x = jnp.asarray(rng.uniform(-1, 1, (M, B, D)).astype("float32"))
    y = jnp.asarray(rng.uniform(-1, 1, (M, B, D)).astype("float32"))

    def stage_fn(w, act):
        return jnp.tanh(act @ w)

    def loss_fn(outs, yy):
        return jnp.mean((outs - yy) ** 2)

    mesh = _mesh(S, "pp")
    pp = PipelineParallel(stage_fn, ws0, loss_fn,
                          optimizer.SGD(learning_rate=0.5, wd=0.0), mesh)
    losses = [float(pp.step(x, y).asnumpy()) for _ in range(5)]
    assert losses[-1] < losses[0], losses

    # single-device reference: identical math (sequential stages,
    # full-batch grads == accumulated microbatch grads)
    def ref_loss(ws):
        act = x
        for s in range(S):
            act = jax.vmap(lambda mb, w=ws[s]: stage_fn(w, mb))(act)
        return loss_fn(act, y)

    ws = ws0
    ref_losses = []
    for _ in range(5):
        l, g = jax.value_and_grad(ref_loss)(ws)
        ref_losses.append(float(l))
        ws = ws - 0.5 * g
    onp.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-6)
    onp.testing.assert_allclose(onp.asarray(jax.device_get(pp.params)),
                                onp.asarray(ws), rtol=1e-4, atol=1e-5)


def test_top2_gating_properties():
    """Top-2: two slots per token (capacity permitting), pair-renormalized
    gates, aux loss near 1 for balanced logits."""
    rng = onp.random.RandomState(0)
    T, E = 32, 4
    C = 2 * T          # worst case: an expert is every token's 1st AND 2nd
    logits = jnp.asarray(rng.normal(0, 1, (T, E)).astype("float32"))
    combine, dispatch, aux = top2_gating(logits, C)
    assert combine.shape == (T, E, C)
    # every token dispatches to exactly 2 slots at full capacity
    per_token = onp.asarray(dispatch.sum(axis=(1, 2)))
    onp.testing.assert_allclose(per_token, 2.0)
    # gate weights renormalize over the kept pair -> combine sums to 1
    per_token_gate = onp.asarray(combine.sum(axis=(1, 2)))
    onp.testing.assert_allclose(per_token_gate, 1.0, rtol=1e-5)
    assert 0.5 < float(aux) < 2.0
    # capacity 1: at most one slot per expert per rank position
    _, d1, _ = top2_gating(logits, 1)
    assert float(d1.sum(axis=(1, 2)).max()) <= 2.0
    assert onp.all(onp.asarray(d1.sum(axis=(0, 2))) <= 1.0 + 1e-6)


def test_moe_top2_trains_and_balances():
    """Training WITH the aux loss in the objective must reduce both the
    task loss and routing imbalance (VERDICT #5: the aux loss has to be
    exercised by an actual training step)."""
    rng = onp.random.RandomState(2)
    T, D, E, H = 64, 8, 4, 16
    x = jnp.asarray(rng.normal(0, 1, (T, D)).astype("float32"))
    y = jnp.asarray(rng.normal(0, 1, (T, D)).astype("float32"))
    params = {
        "gw": jnp.asarray(rng.normal(0, 0.3, (E, D)).astype("float32")),
        "w1": jnp.asarray(rng.normal(0, 0.3, (E, D, H)).astype("float32")),
        "b1": jnp.zeros((E, H), jnp.float32),
        "w2": jnp.asarray(rng.normal(0, 0.3, (E, H, D)).astype("float32")),
        "b2": jnp.zeros((E, D), jnp.float32),
    }

    def objective(p):
        out, aux = moe_dispatch_combine(
            x, x @ p["gw"].T,
            moe_ffn_apply(p["w1"], p["b1"], p["w2"], p["b2"]),
            capacity_factor=2.0, top_k=2)
        task = jnp.mean((out - y) ** 2)
        return task + 0.01 * aux, (task, aux)

    grad_fn = jax.jit(jax.value_and_grad(objective, has_aux=True))
    hist = []
    for _ in range(30):
        (total, (task, aux)), g = grad_fn(params)
        hist.append((float(total), float(task), float(aux)))
        params = jax.tree.map(lambda w, d: w - 0.3 * d, params, g)
    assert hist[-1][0] < hist[0][0], hist[:2] + hist[-2:]
    assert hist[-1][1] < hist[0][1]
    # gate gradients flowed: gate weights moved
    assert float(jnp.abs(params["gw"]).sum()) > 0


def test_gluon_moe_block_trains():
    """User-facing gluon MoEFFN: autograd through dispatch/combine with
    the aux loss in the objective; loss decreases under Trainer."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu import np as mnp

    mx.random.seed(0)
    blk = gluon.contrib.MoEFFN(units=8, hidden_size=16, num_experts=4,
                               top_k=2, capacity_factor=2.0)
    blk.initialize()
    trainer = gluon.Trainer(blk.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    rng = onp.random.RandomState(5)
    x = mnp.array(rng.normal(0, 1, (4, 16, 8)).astype("float32"))
    y = mnp.array(rng.normal(0, 1, (4, 16, 8)).astype("float32"))
    losses = []
    for _ in range(25):
        with autograd.record():
            out, aux = blk(x)
            loss = mnp.mean((out - y) ** 2) + 0.01 * aux
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])
    out2, aux2 = blk(x)
    assert out2.shape == (4, 16, 8)
    assert aux2.shape == ()
