"""C++ frontend (`cpp-package/`): builds the example against the embedded
CPython runtime and runs it end-to-end (NDArray math + model_zoo forward).
Reference: `cpp-package/include/mxnet-cpp/` (~10.7k LoC C-API wrapper);
here the frontend embeds the Python runtime instead — one implementation,
no drift between language frontends."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "cpp-package")


@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
@pytest.mark.skipif(shutil.which("python3-config") is None,
                    reason="needs python3-config (embedding flags)")
def test_cpp_frontend_builds_and_runs():
    build = subprocess.run(["make"], cwd=PKG, capture_output=True, text=True)
    assert build.returncode == 0, build.stderr[-2000:]
    exe = os.path.join(PKG, "build", "mlp_inference")
    assert os.path.exists(exe)
    env = dict(os.environ)
    # the embedded interpreter needs the same import roots as this one
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] + [REPO])
    run = subprocess.run([exe, REPO], capture_output=True, text=True,
                         env=env, timeout=600)
    out = run.stdout
    assert "PASS ndarray_math" in out, (out, run.stderr[-2000:])
    assert "PASS ndarray_sum" in out
    assert "PASS model_zoo_forward" in out
    assert "PASS gpt_generate" in out
    assert "ALL OK" in out
    assert run.returncode == 0


@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
@pytest.mark.skipif(shutil.which("python3-config") is None,
                    reason="needs python3-config (embedding flags)")
def test_cpp_frontend_trains():
    """C++ training loop (Net/Optimizer/Trainer — reference cpp-package
    optimizer.hpp/executor.hpp surface): loss drops, accuracy >0.9, and
    save/load round-trips through the C++ API."""
    build = subprocess.run(["make", "build/mlp_train"], cwd=PKG,
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr[-2000:]
    exe = os.path.join(PKG, "build", "mlp_train")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] + [REPO])
    run = subprocess.run([exe, REPO], capture_output=True, text=True,
                         env=env, timeout=600)
    out = run.stdout
    assert "PASS optimizer_failfast" in out, (out, run.stderr[-2000:])
    assert "PASS train_loss_drops" in out, (out, run.stderr[-2000:])
    assert "PASS train_accuracy" in out
    assert "PASS params_roundtrip" in out
    assert "ALL OK" in out
    assert run.returncode == 0
