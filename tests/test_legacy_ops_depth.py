"""Legacy op long-tail depth tests (reference `src/operator/` root ops:
regression outputs, LRN, UpSampling, im2col/col2im, storage casts,
legacy random distributions)."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np

nd = mx.nd


def _r(*shape, seed=0):
    return np.array(onp.random.RandomState(seed)
                    .uniform(-1, 1, shape).astype("float32"))


def test_slice_axis_reverse_crop():
    x = _r(3, 5)
    onp.testing.assert_allclose(
        nd.slice_axis(x, axis=1, begin=1, end=4).asnumpy(),
        x.asnumpy()[:, 1:4])
    onp.testing.assert_allclose(
        nd.reverse(x, axis=1).asnumpy(), x.asnumpy()[:, ::-1])
    onp.testing.assert_allclose(
        nd.crop(x, begin=(0, 1), end=(2, 3)).asnumpy(),
        x.asnumpy()[0:2, 1:3])


def test_depth_space_roundtrip():
    x = _r(2, 8, 4, 4)
    d = nd.depth_to_space(x, 2)
    assert d.shape == (2, 2, 8, 8)
    onp.testing.assert_allclose(
        nd.space_to_depth(d, 2).asnumpy(), x.asnumpy())


def test_im2col_matches_manual_patch():
    x = _r(1, 1, 4, 4)
    c = nd.im2col(x, kernel=(2, 2))
    assert c.shape == (1, 4, 9)
    # first output column = top-left 2x2 patch, row-major
    xn = x.asnumpy()[0, 0]
    onp.testing.assert_allclose(
        c.asnumpy()[0, :, 0],
        [xn[0, 0], xn[0, 1], xn[1, 0], xn[1, 1]], rtol=1e-6)


def test_col2im_sums_overlaps():
    x = np.ones((1, 1, 3, 3))
    c = nd.im2col(x, kernel=(2, 2))
    back = nd.col2im(c, (3, 3), kernel=(2, 2))
    # center pixel belongs to all 4 patches
    assert back.asnumpy()[0, 0, 1, 1] == 4.0
    assert back.asnumpy()[0, 0, 0, 0] == 1.0


def test_moments():
    x = _r(4, 3)
    m, v = nd.moments(x, axes=(0,))
    onp.testing.assert_allclose(m.asnumpy(), x.asnumpy().mean(0),
                                rtol=1e-5)
    onp.testing.assert_allclose(v.asnumpy(), x.asnumpy().var(0),
                                rtol=1e-4)


def test_activation_variants():
    x = _r(3, 4)
    xn = x.asnumpy()
    onp.testing.assert_allclose(
        nd.hard_sigmoid(x).asnumpy(),
        onp.clip(0.2 * xn + 0.5, 0, 1), rtol=1e-5)
    sp = onp.log1p(onp.exp(xn))
    onp.testing.assert_allclose(nd.mish(x).asnumpy(),
                                xn * onp.tanh(sp), rtol=1e-4)
    onp.testing.assert_allclose(
        nd.log_sigmoid(x).asnumpy(),
        -onp.log1p(onp.exp(-xn)), rtol=1e-4)
    y = np.array(onp.array([8.0, 27.0], "float32"))
    onp.testing.assert_allclose(nd.rcbrt(y).asnumpy(), [0.5, 1 / 3],
                                rtol=1e-5)
    onp.testing.assert_allclose(nd.rsqrt(y).asnumpy(),
                                1 / onp.sqrt([8.0, 27.0]), rtol=1e-5)


def test_softmax_cross_entropy():
    x = _r(4, 5)
    y = np.array(onp.array([0, 2, 1, 4], "int32"))
    out = nd.softmax_cross_entropy(x, y)
    xn = x.asnumpy()
    lp = xn - xn.max(1, keepdims=True)
    lp = lp - onp.log(onp.exp(lp).sum(1, keepdims=True))
    expect = -lp[onp.arange(4), y.asnumpy()].sum()
    onp.testing.assert_allclose(out.asnumpy(), [expect], rtol=1e-4)


def test_lrn_formula():
    x = _r(1, 5, 2, 2)
    out = nd.LRN(x, alpha=1e-2, beta=0.5, knorm=1.0, nsize=3)
    xn = x.asnumpy()
    expect = onp.zeros_like(xn)
    for c in range(5):
        lo, hi = max(0, c - 1), min(5, c + 2)
        acc = (xn[:, lo:hi] ** 2).sum(axis=1)
        expect[:, c] = xn[:, c] / (1.0 + (1e-2 / 3) * acc) ** 0.5
    onp.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-4)


def test_upsampling():
    x = _r(1, 2, 3, 3)
    out = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert out.shape == (1, 2, 6, 6)
    onp.testing.assert_allclose(out.asnumpy()[0, 0, :2, :2],
                                onp.full((2, 2),
                                         x.asnumpy()[0, 0, 0, 0]))
    bil = nd.UpSampling(x, scale=2, sample_type="bilinear",
                        num_filter=2)
    assert bil.shape == (1, 2, 6, 6)


def test_regression_outputs_grads():
    x, y = _r(4, 1), _r(4, 1, seed=1)
    x.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(x, y)
        out.backward()
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    onp.testing.assert_allclose(x.grad.asnumpy(),
                                (x.asnumpy() - y.asnumpy()) / 4,
                                rtol=1e-5)
    x.attach_grad()
    with autograd.record():
        out = nd.MAERegressionOutput(x, y)
        out.backward()
    onp.testing.assert_allclose(
        x.grad.asnumpy(),
        onp.sign(x.asnumpy() - y.asnumpy()) / 4, rtol=1e-5)
    lab = np.array((onp.random.RandomState(2).uniform(0, 1, (4, 1)) > .5)
                   .astype("float32"))
    x.attach_grad()
    with autograd.record():
        out = nd.LogisticRegressionOutput(x, lab)
        out.backward()
    sig = 1 / (1 + onp.exp(-x.asnumpy()))
    onp.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
    onp.testing.assert_allclose(x.grad.asnumpy(),
                                (sig - lab.asnumpy()) / 4, rtol=1e-4)


def test_svm_output_identity_forward_and_grad():
    x = _r(3, 4)
    y = np.array(onp.array([1, 0, 3], "float32"))
    x.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(x, y, margin=1.0)
        out.backward()
    onp.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    g = x.grad.asnumpy()
    assert onp.isfinite(g).all()
    # gradient pushes the true class up (negative grad on true logit)
    assert (g[onp.arange(3), y.asnumpy().astype(int)] <= 0).all()


def test_block_grad_and_make_loss():
    x = _r(3)
    x.attach_grad()
    with autograd.record():
        out = (nd.BlockGrad(x) * x).sum()
    out.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), x.asnumpy(),
                                rtol=1e-5)  # only the live branch
    x.attach_grad()
    with autograd.record():
        loss = nd.make_loss(x, grad_scale=2.0)
        loss.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * onp.ones(3))


def test_argmax_channel_choose_size():
    x = _r(3, 4)
    onp.testing.assert_allclose(nd.argmax_channel(x).asnumpy(),
                                x.asnumpy().argmax(1).astype("float32"))
    idx = np.array(onp.array([1, 0, 3], "float32"))
    onp.testing.assert_allclose(
        nd.choose_element_0index(x, idx).asnumpy(),
        x.asnumpy()[onp.arange(3), [1, 0, 3]])
    assert nd.size_array(x).asnumpy().tolist() == [12]


def test_shuffle_is_permutation():
    x = np.array(onp.arange(32, dtype="float32"))
    mx.random.seed(7)
    out = nd.shuffle(x)
    onp.testing.assert_allclose(sorted(out.asnumpy()), x.asnumpy())


def test_cast_storage():
    x = _r(4, 3)
    rs = nd.cast_storage(x, "row_sparse")
    assert rs.stype == "row_sparse"
    back = nd.cast_storage(rs, "default")
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy())


def test_broadcast_axis():
    x = _r(1, 3)
    out = nd.broadcast_axis(x, axis=0, size=4)
    assert out.shape == (4, 3)
    onp.testing.assert_allclose(out.asnumpy(),
                                onp.tile(x.asnumpy(), (4, 1)))


def test_legacy_random_family():
    mx.random.seed(3)
    a = nd.normal(0, 1, shape=(100,))
    assert abs(float(a.asnumpy().mean())) < 0.5
    assert nd.uniform(0, 1, shape=(5, 2)).shape == (5, 2)
    assert nd.poisson(lam=3.0, shape=(10,)).shape == (10,)
    assert nd.exponential(shape=(4,)).shape == (4,)
    x = _r(2, 3)
    assert nd.normal_like(x).shape == (2, 3)
    assert nd.uniform_like(x).shape == (2, 3)
    g = nd.generalized_negative_binomial(mu=2.0, alpha=0.4, shape=(50,))
    assert g.shape == (50,)
    assert (g.asnumpy() >= 0).all()
    assert nd.generalized_negative_binomial_like(x, mu=1.0,
                                                 alpha=0.3).shape == \
        (2, 3)


def test_upsampling_multi_input():
    a = _r(1, 2, 8, 8)
    b = _r(1, 2, 4, 4, seed=1)
    out = nd.UpSampling(a, b, scale=2, sample_type="nearest",
                        num_args=2)
    assert out.shape == (1, 4, 16, 16)     # both land at 16x16, concat
    summed = nd.UpSampling(a, b, scale=2, sample_type="nearest",
                           num_args=2, multi_input_mode="sum")
    assert summed.shape == (1, 2, 16, 16)


def test_multi_sgd_single_out_ndarray():
    w, g = _r(3, 2), _r(3, 2, seed=1)
    wn = w.asnumpy().copy()
    nd.multi_sgd_update(w, g, lrs=(0.1,), wds=(0.0,), num_weights=1,
                        out=w)
    onp.testing.assert_allclose(w.asnumpy(), wn - 0.1 * g.asnumpy(),
                                rtol=1e-5)


def test_upsampling_bilinear_with_weight():
    # reference kBilinear mode: grouped deconv with the provided kernel
    x = _r(1, 2, 4, 4)
    s = 2
    k = 2 * s - s % 2
    w = np.ones((2, 1, k, k))
    out = nd.UpSampling(x, w, scale=s, sample_type="bilinear",
                        num_filter=2, num_args=2)
    assert out.shape == (1, 2, 8, 8)
