"""Test config: force a virtual 8-device CPU platform BEFORE jax initializes
(the reference's analogue: CPU is the reference implementation, SURVEY.md §4),
and wait for async work between modules (reference: conftest.py:61
`mx.nd.waitall()` between modules to catch async leakage)."""
import os

# The host sitecustomize pins JAX_PLATFORMS to the TPU plugin; tests run on a
# virtual 8-device CPU platform, so override through every channel jax reads.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_PLATFORM_NAME"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags +
                               " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def waitall_between_modules():
    yield
    import incubator_mxnet_tpu as mx

    mx.waitall()


@pytest.fixture(autouse=True)
def seed_rng():
    import numpy as onp

    import incubator_mxnet_tpu as mx

    onp.random.seed(0)
    mx.random.seed(0)
    yield
