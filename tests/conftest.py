"""Test config: force a virtual 8-device CPU platform BEFORE jax initializes
(the reference's analogue: CPU is the reference implementation, SURVEY.md §4),
and wait for async work between modules (reference: conftest.py:61
`mx.nd.waitall()` between modules to catch async leakage)."""
import os

# The host sitecustomize pins JAX_PLATFORMS to the TPU plugin; tests run on a
# virtual 8-device CPU platform, so override through every channel jax reads.
# Exception: MX_TPU_TESTS=1 keeps the real accelerator visible ALONGSIDE cpu
# so tests/test_tpu_consistency.py can compare the two backends on-chip.
if os.environ.get("MX_TPU_TESTS") == "1":
    # FORCE both platforms: sitecustomize may have pinned JAX_PLATFORMS
    # to the accelerator alone, which would hide the cpu reference side
    accel = os.environ.get("MX_TPU_PLATFORM") \
        or os.environ.get("JAX_PLATFORMS", "").split(",")[0] or "axon"
    if "cpu" not in os.environ.get("JAX_PLATFORMS", "").split(","):
        os.environ["JAX_PLATFORMS"] = accel + ",cpu"
    import jax  # noqa: E402
else:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags +
                                   " --xla_force_host_platform_device_count=8")

    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Fast certification subset (`pytest -m quick`, <2 min on 1 vCPU): one
# representative test per subsystem so a judge/driver can certify the
# tree without the full 10-minute run. Centralized here instead of
# scattering markers across 60 files.
_QUICK = {
    "test_ndarray.py::test_creation",
    "test_autograd.py::test_record_flags",
    "test_gluon.py::test_parameter",
    "test_symbol.py::test_variable_and_compose",
    "test_ops.py::test_unary_vs_numpy",
    "test_kvstore_backends.py::test_custom_backend_create_and_roundtrip",
    "test_parallel.py::test_make_mesh",
    "test_optimizer.py::test_optimizer_decreases_quadratic",
    "test_optim_ops.py::test_sgd_update_out_semantics",
    "test_io_iters.py::test_csv_iter",
    "test_image.py::test_resize_and_crops",
    "test_partition.py::test_builtin_backends_registered",
    "test_probability.py::test_normal_log_prob_cdf_icdf",
    "test_profiler.py::test_record_op_from_funnel",
    "test_onnx.py::test_mlp_batchnorm_export",
    "test_control_flow.py::test_foreach_eager",
    "test_gpt.py::test_forward_shape_and_determinism",
    "test_estimator.py::test_estimator_fit_learns",
    "test_native.py::test_rtio_reader_matches_python",
    "test_model_store_artifact.py::test_packaged_artifact_resolves_and_verifies",
    "test_rnn_depth.py::test_rnn_layer_output_shape",
    "test_loss_metric_depth.py::test_l2_loss_value",
    "test_sparse.py::test_row_sparse_creation_and_densify",
    "test_quantization.py::test_entropy_threshold_clips_outliers",
    "test_graph_ops.py::test_edge_id",
    "test_contrib_ops_depth.py::test_quadratic",
    "test_legacy_ops_depth.py::test_slice_axis_reverse_crop",
    # static-analysis subsystem: whole-tree framework lint + auditor smoke
    # on a hybridized model_zoo block (ISSUE 1 CI gates)
    "test_analysis.py::test_framework_lint_tree_is_clean",
    "test_analysis.py::test_audit_hybridized_model_zoo_clean",
    # fault-tolerance subsystem (ISSUE 3 gates): worker-death + kvstore
    # retry suites, checkpoint fallback, and the chaos-convergence gate
    "test_fault.py::test_kvstore_push_retries_injected_fault",
    "test_fault.py::test_dataloader_worker_fault_retry",
    "test_fault.py::test_checkpoint_checksum_fallback",
    "test_fault.py::test_estimator_chaos_convergence",
    # serving subsystem (ISSUE 4 gates): stub-scheduler logic runs with
    # no XLA compile, so these certify backpressure/deadline/drain fast
    "test_serve.py::test_queue_backpressure_raises",
    "test_serve.py::test_deadline_expiry_classifies_retryable",
    "test_serve.py::test_drain_semantics_scheduler",
    "test_serve.py::test_serve_step_fault_seam",
    # paged serving (ISSUE 6 gates): allocator/prefix-cache host logic,
    # remaining-chunk SJF accounting, chunk/decode interleave, FL009 —
    # all stub-level, no XLA compile
    "test_serve.py::test_page_allocator_alloc_free_refcount",
    "test_serve.py::test_page_allocator_oom_loud",
    "test_serve.py::test_prefix_cache_register_lookup_evict",
    "test_serve.py::test_sjf_orders_by_remaining_prefill_chunks",
    "test_serve.py::test_chunked_prefill_interleaves_with_decode",
    "test_tools.py::test_fl009_tree_is_clean",
    "test_tools.py::test_fl007_tree_is_clean",
    # observability round 2 (ISSUE 5 gates): span tracer mechanics, one
    # trace per serve request (stub scheduler — no XLA), SLO burn math,
    # and the FL008 span-hygiene tree sweep
    "test_tracing.py::test_span_nesting_and_ids",
    "test_tracing.py::test_serve_request_trace_stub",
    "test_tracing.py::test_slo_latency_burn_math",
    "test_tools.py::test_fl008_tree_is_clean",
    # shardcheck (ISSUE 8 gates): spec-tier rule fixtures are pure host
    # math over avals (no trace, no compile) and the static meta-gate
    # runs framework lint + AST/eval_shape shardcheck over the tree
    "test_shardcheck.py::test_sc001_unconstrained_param_flagged",
    "test_shardcheck.py::test_sc002_divisibility_violation_flagged",
    "test_shardcheck.py::test_sc003_unknown_axis_flagged",
    "test_shardcheck.py::test_sc006_budget_exceeded_flagged",
    "test_shardcheck.py::test_rule_catalogue_complete",
    "test_shardcheck.py::test_static_gates_meta",
    "test_tools.py::test_fl010_tree_is_clean",
    # multi-tenant gateway (ISSUE 9 gates): WDRR fairness, preemption
    # with token survival, the deadline-while-preempted classification,
    # quota deferral, and the gateway fault seam — all stub-level, no
    # XLA compile — plus the FL011 boundedness tree sweep
    "test_gateway.py::test_wdrr_weighted_share",
    "test_gateway.py::test_preemption_resumes_with_tokens_intact",
    "test_gateway.py::test_preempted_deadline_expiry_classifies_retryable",
    "test_gateway.py::test_tenant_quota_defers_never_drops",
    "test_gateway.py::test_gateway_step_fault_seam",
    "test_tools.py::test_fl011_tree_is_clean",
    # compile & HBM observatory (ISSUE 10 gates): recompile forensics
    # on a tiny jit, census attribution (host-side sweep), the FL012
    # observatory-coverage tree sweep, and the bench trajectory gate on
    # the committed BENCH_r*.json history
    "test_telemetry_observatory.py::test_recompile_cause_shape",
    "test_telemetry_observatory.py::test_census_attribution_first_claim_and_weak_binding",
    "test_tools.py::test_fl012_tree_is_clean",
    "test_tools.py::test_bench_regress_green_on_committed_history",
    # fleet observability (ISSUE 12 gates): straggler z-score math,
    # chunked snapshot transport, collective_delay seam, clock-offset
    # stitching and flightrec merge on synthetic dumps, and the FL014
    # collective-hygiene tree sweep — all host-side, no multi-process
    "test_fleet.py::test_straggler_scores_slow_rank_wins",
    "test_fleet.py::test_exchange_large_chunks_past_command_slot",
    "test_fleet.py::test_collective_delay_sleeps_not_raises",
    "test_fleet.py::test_stitch_traces_rebases_by_clock_offset",
    "test_fleet.py::test_merge_flight_dumps_groups_by_rank",
    "test_tools.py::test_fl014_tree_is_clean",
    # kernel & goodput observatory (ISSUE 14 gates): roofline census
    # math + honest coverage on the committed fixture, the seeded
    # quantize-fusion diff, goodput lease/sum-to-wall semantics, the
    # kernelscope --demo render, and the FL016 series-index tree sweep
    "test_kernels.py::test_census_fixture_roofline_placement",
    "test_kernels.py::test_census_unknown_bytes_never_reads_fast",
    "test_kernels.py::test_diff_census_names_seeded_fusion",
    "test_kernels.py::test_goodput_states_sum_to_wall",
    "test_kernels.py::test_goodput_waterfall_renders_fixture",
    "test_kernels.py::test_kernelscope_demo_renders",
    "test_tools.py::test_fl016_tree_is_clean",
    # pod-scale sharded serving (ISSUE 15 gates): layout rule coverage,
    # 1-device-mesh parity with the unsharded engine, replica routing,
    # and the FL017 placement-provenance tree sweep — all host/CPU-mesh
    "test_sharded_serve.py::test_every_param_leaf_matches_exactly_one_rule",
    "test_sharded_serve.py::test_one_device_mesh_greedy_parity",
    "test_sharded_serve.py::test_router_prefers_warm_prefix_replica",
    "test_tools.py::test_fl017_tree_is_clean",
    # concurrency correctness (ISSUE 16 gates): the whole-tree static
    # racecheck sweep, the audited suspect seams, the ABBA the runtime
    # witness must catch without deadlocking, the by-construction
    # off-path guarantee, and the FL018 tracked-lock provenance sweep
    "test_racecheck.py::test_tree_static_sweep_is_clean",
    "test_racecheck.py::test_suspect_seam_analyzes_clean",
    "test_racecheck.py::test_abba_witnessed_without_deadlock",
    "test_racecheck.py::test_disarmed_tracked_lock_is_raw_primitive",
    "test_tools.py::test_fl018_tree_is_clean",
}


def pytest_collection_modifyitems(items):
    for item in items:
        key = f"{item.fspath.basename}::{item.name.split('[')[0]}"
        if key in _QUICK:
            item.add_marker(pytest.mark.quick)


@pytest.fixture(autouse=True, scope="module")
def waitall_between_modules():
    yield
    import incubator_mxnet_tpu as mx

    mx.waitall()


@pytest.fixture(autouse=True)
def seed_rng():
    import numpy as onp

    import incubator_mxnet_tpu as mx

    onp.random.seed(0)
    mx.random.seed(0)
    yield
