"""Fault-tolerance subsystem (ISSUE 3): deterministic injection, retry
policies, checksummed checkpoint fallback, DataLoader self-healing, and
the Estimator chaos-convergence acceptance gate (RESILIENCE.md)."""
import logging
import os
import sys
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, gluon, np, preemption
from incubator_mxnet_tpu.fault import injection, retry
from incubator_mxnet_tpu.telemetry import registry
from incubator_mxnet_tpu.test_utils import environment

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counter(name):
    rep = registry.report()
    return rep.get(name, {}).get("value", 0) or 0


@pytest.fixture(autouse=True)
def _clear_schedule():
    injection.clear_injection()
    yield
    injection.clear_injection()


@pytest.fixture()
def _fast_retries():
    with environment("MXNET_RETRY_BASE_DELAY_MS", "1"):
        yield


# ---------------------------------------------------------------------------
# injection
# ---------------------------------------------------------------------------

def test_injection_spec_parse_and_determinism():
    def fire_pattern():
        injection.configure_injection("kvstore_push:0.5:42")
        fired = []
        for i in range(100):
            try:
                injection.inject_at("kvstore_push")
            except fault.FaultInjected:
                fired.append(i)
        return fired

    a = fire_pattern()
    b = fire_pattern()
    assert a and a == b                     # seeded: replays exactly
    assert 20 < len(a) < 80                 # ~Bernoulli(0.5)
    info = injection.schedule_info()
    assert info["kvstore_push"]["draws"] == 100
    assert info["kvstore_push"]["fired"] == len(b)


def test_injection_limit_caps_fires():
    injection.configure_injection("estimator_step:1.0:0:2")
    outcomes = []
    for _ in range(5):
        try:
            injection.inject_at("estimator_step")
            outcomes.append("ok")
        except fault.FaultInjected:
            outcomes.append("boom")
    assert outcomes == ["boom", "boom", "ok", "ok", "ok"]


def test_injection_bad_spec_raises():
    with pytest.raises(ValueError, match="unknown seam"):
        injection.configure_injection("not_a_seam:0.5")
    with pytest.raises(ValueError, match="prob"):
        injection.configure_injection("h2d:1.5")
    with pytest.raises(ValueError, match="expected"):
        injection.configure_injection("h2d")
    # a schedule never half-arms after a bad spec
    assert not injection.injection_enabled()


def test_h2d_seam_arms_the_ndarray_hook():
    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod

    assert nd_mod._FAULT_HOOK is None
    injection.configure_injection("h2d:1.0:0:1")
    assert nd_mod._FAULT_HOOK is not None
    with pytest.raises(fault.FaultInjected, match="seam 'h2d'"):
        np.array([1.0, 2.0])
    ok = np.array([1.0, 2.0])               # limit reached: next is clean
    assert onp.allclose(ok.asnumpy(), [1.0, 2.0])
    injection.clear_injection()
    assert nd_mod._FAULT_HOOK is None


def test_injection_off_is_dead_branch():
    """MXNET_FAULT_INJECT-unset contract (the ISSUE 3 overhead gate,
    reusing the PR-2 stage-trace harness shape): the h2d probe is one
    global-load + is-None check per NDArray inlet — measured <3% of a
    funnel op."""
    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod

    assert nd_mod._FAULT_HOOK is None       # off by default
    assert injection.schedule_info() == {}
    a = np.array(onp.random.RandomState(0).uniform(-1, 1, (16, 16))
                 .astype("float32"))
    np.dot(a, a).wait_to_read()             # warm compile caches
    iters = 300
    t0 = time.perf_counter()
    for _ in range(iters):
        np.dot(a, a)
    mx.waitall()
    per_op = (time.perf_counter() - t0) / iters
    fh = nd_mod._FAULT_HOOK
    t0 = time.perf_counter()
    for _ in range(iters):
        if fh is not None:                  # the literal off-path pattern
            pass
    probe_per_op = (time.perf_counter() - t0) / iters
    assert probe_per_op < 0.03 * per_op, (probe_per_op, per_op)


def test_env_knob_arms_injection():
    from incubator_mxnet_tpu import util

    assert "MXNET_FAULT_INJECT" in util.env_knobs()
    assert "MXNET_RETRY_MAX" in util.env_knobs()
    with environment("MXNET_FAULT_INJECT", "h2d:0.0:7"):
        util._apply_env_config()
        assert injection.injection_enabled("h2d")
        assert not injection.injection_enabled("kvstore_push")


# ---------------------------------------------------------------------------
# retry policies
# ---------------------------------------------------------------------------

def test_classify_exception():
    assert retry.classify_exception(ConnectionResetError()) == "retryable"
    assert retry.classify_exception(TimeoutError()) == "retryable"
    assert retry.classify_exception(fault.FaultInjected("h2d", 1)) \
        == "retryable"
    assert retry.classify_exception(RuntimeError("fabric")) == "retryable"
    assert retry.classify_exception(ValueError("bug")) == "fatal"
    assert retry.classify_exception(TypeError("bug")) == "fatal"
    import multiprocessing as mp

    assert retry.classify_exception(mp.TimeoutError()) == "retryable"


def test_retry_policy_backoff_and_success():
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    pol = fault.RetryPolicy(max_retries=3, base_delay=0.05, multiplier=2.0,
                            jitter=0.0, sleep=sleeps.append, name="t")
    before = _counter("mx_retries_total")
    assert pol.call(flaky) == "ok"
    assert len(calls) == 3
    assert sleeps == [0.05, 0.1]            # deterministic exp backoff
    assert _counter("mx_retries_total") == before + 2


def test_retry_policy_fatal_not_retried():
    calls = []

    def buggy():
        calls.append(1)
        raise ValueError("deterministic bug")

    pol = fault.RetryPolicy(max_retries=5, jitter=0.0, sleep=lambda d: None)
    with pytest.raises(ValueError):
        pol.call(buggy)
    assert len(calls) == 1                  # no budget burned on a bug


def test_retry_policy_exhaustion_and_deadline():
    pol = fault.RetryPolicy(max_retries=2, base_delay=0.0, jitter=0.0,
                            sleep=lambda d: None, name="x")

    def always():
        raise ConnectionError("down")

    with pytest.raises(fault.RetryExhausted) as ei:
        pol.call(always)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, ConnectionError)

    hard = fault.RetryPolicy(max_retries=100, base_delay=0.0, jitter=0.0,
                             deadline=0.0, sleep=lambda d: None)
    with pytest.raises(fault.RetryExhausted):
        hard.call(always)                   # deadline, not attempts


def test_retry_from_env_and_suppressed(caplog):
    import logging

    with environment({"MXNET_RETRY_MAX": "7",
                      "MXNET_RETRY_BASE_DELAY_MS": "125",
                      "MXNET_RETRY_DEADLINE_S": "9"}):
        pol = fault.RetryPolicy.from_env("envtest")
    assert pol.max_retries == 7
    assert pol.base_delay == 0.125
    assert pol.deadline == 9.0
    with caplog.at_level(logging.DEBUG, "incubator_mxnet_tpu.fault"):
        kind = fault.suppressed("test.site", ConnectionError("noise"))
    assert kind == "retryable"
    assert any("suppressed@test.site" in r.getMessage()
               for r in caplog.records)


# ---------------------------------------------------------------------------
# kvstore retry suite (quick-marked for tier-1)
# ---------------------------------------------------------------------------

def test_kvstore_push_retries_injected_fault(_fast_retries):
    injection.configure_injection("kvstore_push:1.0:0:2")
    before_r = _counter("mx_retries_total")
    before_f = _counter("mx_faults_injected_total")
    kv = mx.kv.create("local")
    kv.init("w", np.array([1.0, 2.0]))      # init is probe-free
    kv.push("w", np.array([0.5, 0.5]))      # fails twice, succeeds on 3rd
    assert _counter("mx_retries_total") == before_r + 2
    assert _counter("mx_faults_injected_total") == before_f + 2
    out = kv.pull("w")
    assert out is not None                  # store intact after retries


def test_kvstore_retry_exhaustion_surfaces(_fast_retries):
    injection.configure_injection("kvstore_pull:1.0:0:99")
    kv = mx.kv.create("local")
    kv.init("w", np.array([1.0]))
    with pytest.raises(fault.RetryExhausted):
        kv.pull("w")


def test_kvstore_barrier_probe(_fast_retries):
    injection.configure_injection("kvstore_barrier:1.0:0:1")
    before = _counter("mx_retries_total")
    kv = mx.kv.create("local")
    kv.barrier()                            # one fault, one retry, success
    assert _counter("mx_retries_total") == before + 1


# ---------------------------------------------------------------------------
# checkpoint checksum + generation fallback
# ---------------------------------------------------------------------------

def _make_checkpointer(tmp_path, every_n=1, keep=3):
    net = gluon.nn.Dense(4)
    net.initialize()
    net(np.array(onp.ones((2, 3), "float32")))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    ck = preemption.TrainingCheckpointer(
        str(tmp_path / "ck"), net, trainer, every_n=every_n, keep=keep,
        register_signal=False)
    return net, trainer, ck


def test_checkpoint_checksum_fallback(tmp_path, caplog):
    """ISSUE 3 satellite: a corrupted/truncated newest checkpoint raises a
    clear error (logged), then resume auto-falls-back to the prior
    generation."""
    import logging

    _net, _trainer, ck = _make_checkpointer(tmp_path)
    ck.step()
    ck.step()
    ck.step()
    gens = ck._mgr.generations()
    assert len(gens) == 3
    assert preemption.verify_checkpoint(gens[-1]) is True
    with open(gens[-1], "r+b") as f:
        f.truncate(10)                      # torn write
    assert preemption.verify_checkpoint(gens[-1]) is False
    before = _counter("mx_checkpoint_fallbacks_total")
    with caplog.at_level(logging.ERROR, "incubator_mxnet_tpu.fault"):
        step = ck.resume()
    assert step == 2                        # prior generation restored
    assert _counter("mx_checkpoint_fallbacks_total") == before + 1
    joined = " ".join(r.getMessage() for r in caplog.records)
    assert "checksum validation" in joined
    assert "falling back" in joined


def test_checkpoint_all_corrupt_raises_clear_error(tmp_path):
    _net, _trainer, ck = _make_checkpointer(tmp_path)
    ck.step()
    ck.step()
    for g in ck._mgr.generations():
        with open(g, "r+b") as f:
            f.truncate(5)
    with pytest.raises(mx.base.MXNetError, match="all 2 generation"):
        ck.resume()


def test_atomic_save_retries_injected_write_fault(tmp_path, _fast_retries):
    injection.configure_injection("checkpoint_write:1.0:0:1")
    before = _counter("mx_retries_total")
    path = preemption.atomic_save(
        str(tmp_path / "x.bin"), lambda t: open(t, "wb").write(b"hello"))
    assert open(path, "rb").read() == b"hello"
    assert preemption.verify_checkpoint(path) is True
    assert _counter("mx_retries_total") == before + 1


def test_save_parameters_checksum_roundtrip(tmp_path):
    net = gluon.nn.Dense(3)
    net.initialize()
    net(np.array(onp.ones((2, 4), "float32")))
    p = str(tmp_path / "net.params")
    net.save_parameters(p)
    assert preemption.verify_checkpoint(p) is True
    net.load_parameters(p)                  # clean load passes validation
    with open(p, "r+b") as f:
        f.truncate(max(1, os.path.getsize(p) // 2))
    with pytest.raises(mx.base.MXNetError, match="checksum"):
        net.load_parameters(p)


def test_trainer_states_checksum_roundtrip(tmp_path):
    net = gluon.nn.Dense(2)
    net.initialize()
    net(np.array(onp.ones((2, 3), "float32")))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    s = str(tmp_path / "trainer.states")
    trainer.save_states(s)
    assert preemption.verify_checkpoint(s) is True
    trainer.load_states(s)
    with open(s, "r+b") as f:
        f.truncate(3)
    with pytest.raises(mx.base.MXNetError, match="checksum"):
        trainer.load_states(s)


# ---------------------------------------------------------------------------
# DataLoader self-healing (worker-death suite, quick-marked for tier-1)
# ---------------------------------------------------------------------------

class _BadItemDataset:
    """Deterministic dataset bug: index 3 always raises ValueError."""

    def __init__(self, n=16):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if i == 3:
            raise ValueError("index 3 is broken, every time")
        return onp.full((4,), i, "float32")


def test_dataloader_worker_fault_retry():
    """Injected worker faults (env-armed in the spawned workers) are
    retried against the pool; every batch arrives, in order."""
    from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset

    X = onp.arange(64, dtype="float32").reshape(16, 4)
    before = _counter("mx_retries_total")
    # spawn (not forkserver): the forkserver freezes its env at first
    # use, so per-test MXNET_FAULT_INJECT would never reach the workers
    with environment({"MXNET_FAULT_INJECT": "dataloader_worker:1.0:0:2",
                      "MXNET_MP_START_METHOD": "spawn"}):
        injection.clear_injection()         # parent probes stay dead
        loader = DataLoader(ArrayDataset(X), batch_size=4, num_workers=2,
                            timeout=120)
        got = [b.asnumpy() for b in loader]
    assert len(got) == 4
    assert onp.allclose(onp.concatenate(got), X)   # order preserved
    assert _counter("mx_retries_total") > before


def test_dataloader_retries_exhausted_falls_back_inprocess():
    """A worker seam hot enough to outlive the retry budget degrades to
    the loud single-process fallback — data still correct and ordered."""
    from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset

    X = onp.arange(32, dtype="float32").reshape(8, 4)
    before = _counter("mx_dataloader_fallbacks_total")
    with environment({"MXNET_FAULT_INJECT": "dataloader_worker:1.0:0:99",
                      "MXNET_WORKER_RETRIES": "1",
                      "MXNET_MP_START_METHOD": "spawn"}):
        injection.clear_injection()
        loader = DataLoader(ArrayDataset(X), batch_size=4, num_workers=1,
                            timeout=120)
        got = [b.asnumpy() for b in loader]
    assert onp.allclose(onp.concatenate(got), X)
    assert _counter("mx_dataloader_fallbacks_total") > before


def test_dataloader_fatal_error_propagates():
    """A deterministic dataset bug is classified fatal and re-raised —
    not laundered through the retry budget."""
    from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader

    loader = DataLoader(_BadItemDataset(), batch_size=4, num_workers=1,
                        timeout=120)
    with pytest.raises(ValueError, match="index 3 is broken"):
        list(loader)


# ---------------------------------------------------------------------------
# estimator resilience + the chaos-convergence acceptance gate
# ---------------------------------------------------------------------------

def _fit_linear(X, Y, tmp_path, tag, handlers_extra=(), epochs=2):
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset

    onp.random.seed(0)
    mx.random.seed(0)
    net = gluon.nn.Dense(1)
    net.initialize()
    net(np.array(X[:2]))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    ck = preemption.TrainingCheckpointer(
        str(tmp_path / f"ck_{tag}"), net, trainer, every_n=1, keep=3,
        register_signal=False)
    handler = fault.ResilienceHandler(checkpointer=ck)
    est = Estimator(net, gluon.loss.L2Loss(), trainer=trainer,
                    train_metrics=[gluon.metric.MAE()])
    est.logger.setLevel(logging.ERROR)                 # quiet: recovery still counted
    loader = DataLoader(ArrayDataset(X, Y), batch_size=8, num_workers=0)
    est.fit(loader, epochs=epochs,
            event_handlers=[handler, *handlers_extra])
    return net, ck


def test_estimator_chaos_convergence(tmp_path, _fast_retries):
    """ISSUE 3 acceptance gate: an Estimator run under an
    MXNET_FAULT_INJECT schedule (worker faults + one mid-step crash + one
    corrupted checkpoint generation) auto-recovers and lands within
    tolerance of the unfaulted run's final loss, with the recovery
    metrics nonzero in the registry dump."""
    rng = onp.random.RandomState(7)
    X = rng.uniform(-1, 1, (128, 8)).astype("float32")
    w = rng.uniform(-1, 1, (8, 1)).astype("float32")
    Y = X @ w
    X[5] = onp.nan                          # one non-finite batch per epoch
    Xv = rng.uniform(-1, 1, (64, 8)).astype("float32")
    Yv = Xv @ w

    def val_loss(net):
        d = net(np.array(Xv)).asnumpy() - Yv
        return float(0.5 * (d * d).mean())

    # -- unfaulted reference run (same data, same guard, no chaos) --
    net_a, _ = _fit_linear(X, Y, tmp_path, "clean", epochs=4)
    loss_a = val_loss(net_a)

    # -- chaos run --
    skipped0 = _counter("mx_steps_skipped_nonfinite_total")
    resumes0 = _counter("mx_resumes_total")
    retries0 = _counter("mx_retries_total")
    fallback0 = _counter("mx_checkpoint_fallbacks_total")

    spec = ("dataloader_worker:1.0:0:1,"    # worker fault (env-armed)
            "estimator_step:1.0:0:1")       # one mid-step crash (batch 1)
    with environment({"MXNET_FAULT_INJECT": spec,
                      "MXNET_MP_START_METHOD": "spawn"}):
        injection.configure_injection(spec)

        # the crash fires on the FIRST batch — pre-seed two checkpoint
        # generations (init state) and corrupt the newest so the resume
        # path must checksum-fail it and fall back to the older one
        from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
        from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader
        from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset

        onp.random.seed(0)
        mx.random.seed(0)
        net_b = gluon.nn.Dense(1)
        net_b.initialize()
        net_b(np.array(X[:2]))
        trainer_b = gluon.Trainer(net_b.collect_params(), "sgd",
                                  {"learning_rate": 0.1})
        ck_b = preemption.TrainingCheckpointer(
            str(tmp_path / "ck_chaos"), net_b, trainer_b, every_n=1,
            keep=3, register_signal=False)
        ck_b.step()
        ck_b.step()                         # two pre-run generations
        newest = ck_b._mgr.generations()[-1]
        with open(newest, "r+b") as f:
            f.truncate(8)                   # the "one corrupted checkpoint"

        handler = fault.ResilienceHandler(checkpointer=ck_b)
        est = Estimator(net_b, gluon.loss.L2Loss(), trainer=trainer_b,
                        train_metrics=[gluon.metric.MAE()])
        est.logger.setLevel(logging.ERROR)
        loader = DataLoader(ArrayDataset(X, Y), batch_size=8,
                            num_workers=1, timeout=120)
        est.fit(loader, epochs=4, event_handlers=[handler])
    loss_b = val_loss(net_b)

    # auto-recovery happened, and it was measured (the gate's metrics)
    assert _counter("mx_steps_skipped_nonfinite_total") > skipped0
    assert _counter("mx_resumes_total") > resumes0
    assert _counter("mx_retries_total") > retries0
    assert _counter("mx_checkpoint_fallbacks_total") > fallback0
    info = injection.schedule_info()
    assert info["estimator_step"]["fired"] == 1

    # ...and the chaos run converged to the unfaulted run's loss
    assert loss_a < 0.05, loss_a            # both actually learned
    assert loss_b < 0.05, loss_b
    assert abs(loss_a - loss_b) <= 0.02, (loss_a, loss_b)


def test_resilience_consecutive_skip_bound(tmp_path):
    """An always-NaN model fails loudly instead of spinning forever."""
    rng = onp.random.RandomState(0)
    X = onp.full((32, 4), onp.nan, "float32")
    Y = rng.uniform(-1, 1, (32, 1)).astype("float32")
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset

    net = gluon.nn.Dense(1)
    net.initialize()
    net(np.array(X[:2]))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    handler = fault.ResilienceHandler(max_consecutive_skips=2)
    est = Estimator(net, gluon.loss.L2Loss(), trainer=trainer,
                    train_metrics=[gluon.metric.MAE()])
    est.logger.setLevel(logging.ERROR)
    loader = DataLoader(ArrayDataset(X, Y), batch_size=8, num_workers=0)
    with pytest.raises(mx.base.MXNetError, match="non-finite-loss steps"):
        est.fit(loader, epochs=5, event_handlers=[handler])


def test_resilience_amp_backoff(tmp_path):
    """A skipped non-finite step halves the live AMP loss scale."""
    from incubator_mxnet_tpu import amp
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator
    from incubator_mxnet_tpu.gluon.data.dataloader import DataLoader
    from incubator_mxnet_tpu.gluon.data.dataset import ArrayDataset

    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (32, 4)).astype("float32")
    Y = (X @ rng.uniform(-1, 1, (4, 1)).astype("float32"))
    X[1] = onp.nan
    net = gluon.nn.Dense(1)
    net.initialize()
    net(np.array(X[:2]))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    amp.init("bfloat16")
    try:
        amp.scale_loss._scaler = None       # fresh scaler for the assert
        with amp.scale_loss(np.array([1.0]), trainer):
            pass                            # instantiates the scaler
        scale0 = amp.scale_loss._scaler.loss_scale
        handler = fault.ResilienceHandler()
        est = Estimator(net, gluon.loss.L2Loss(), trainer=trainer,
                        train_metrics=[gluon.metric.MAE()])
        est.logger.setLevel(logging.ERROR)
        loader = DataLoader(ArrayDataset(X, Y), batch_size=8,
                            num_workers=0)
        est.fit(loader, epochs=1, event_handlers=[handler])
        assert amp.scale_loss._scaler.loss_scale < scale0
    finally:
        amp.deinit()


# ---------------------------------------------------------------------------
# elastic topology (ISSUE 13): membership epochs, checkpoint resharding,
# the topology_change seam, and the shrink chaos-convergence gate
# ---------------------------------------------------------------------------

def _gauge(name):
    rep = registry.report()
    return rep.get(name, {}).get("value", 0) or 0


@pytest.fixture(autouse=True)
def _pristine_membership():
    from incubator_mxnet_tpu.parallel import dist
    dist._reset_membership()
    yield
    dist._reset_membership()


def test_topology_seam_parse_and_classification():
    from incubator_mxnet_tpu.fault.injection import TopologyChanged

    injection.configure_injection("topology_change:1.0:3:2:shrink=4")
    info = injection.schedule_info()["topology_change"]
    assert info["kind"] == "topology"
    assert info["shrink"] == 4
    with pytest.raises(TopologyChanged) as ei:
        injection.inject_at("topology_change")
    assert ei.value.shrink == 4
    # a topology change is NOT a transient fault: retrying the step
    # cannot bring the departed rank back
    assert ei.value.non_retryable
    assert retry.classify_exception(ei.value) == "fatal"
    # pickles across process boundaries (worker pools)
    import pickle
    e2 = pickle.loads(pickle.dumps(ei.value))
    assert isinstance(e2, TopologyChanged) and e2.shrink == 4


def test_topology_seam_rank_targeting():
    # @rank matches this process (rank 0 single-process): fires
    injection.configure_injection("topology_change@0:1.0:0:1")
    with pytest.raises(fault.FaultInjected):
        injection.inject_at("topology_change")
    injection.clear_injection()
    # targeted at another rank: this process never fires it
    injection.configure_injection("topology_change@5:1.0:0:9")
    for _ in range(16):
        injection.inject_at("topology_change")


def test_stale_generation_fails_loudly():
    """A rank that missed the membership transition must FAIL its next
    collective (non-retryable), not hang the surviving fleet."""
    import jax.numpy as jnp

    from incubator_mxnet_tpu.parallel import dist

    gen0 = dist.generation()
    # single-process rendezvous turns the epoch in place
    gen1, members = dist.rendezvous()
    assert gen1 == gen0 + 1 and dist.generation() == gen1
    # a collective still holding the OLD epoch fails loudly...
    with pytest.raises(dist.StaleGenerationError) as ei:
        dist.allreduce(jnp.ones(2), generation=gen0)
    assert retry.classify_exception(ei.value) == "fatal"
    # ...and the CURRENT epoch passes
    out = dist.allreduce(jnp.ones(2), generation=gen1)
    assert float(out.sum()) == 2.0
    # a departed rank is fenced out of every later collective
    dist.rendezvous(leave=True)
    with pytest.raises(dist.StaleGenerationError):
        dist.barrier()


def test_elastic_sampler_covers_exactly_once():
    from incubator_mxnet_tpu.gluon.data import ElasticSampler

    # two ranks, lockstep: each draws 3 of 16, then rank 1 departs and
    # rank 0 reshards to a 1-shard world — every index appears EXACTLY
    # once across what was consumed and what remains
    s0 = ElasticSampler(16, num_shards=2, index=0, shuffle=True, seed=5)
    s1 = ElasticSampler(16, num_shards=2, index=1, shuffle=True, seed=5)
    it0, it1 = iter(s0), iter(s1)
    drawn = [next(it0) for _ in range(3)] + [next(it1) for _ in range(3)]
    s0.reshard(num_shards=1, index=0)
    rest = list(s0)
    assert sorted(drawn + rest) == list(range(16))
    assert len(s0) == 0 and s0.remaining() == 0


def _make_dp(mesh, seed=0, units=1, in_units=4, param_shardings=None):
    from incubator_mxnet_tpu import optimizer as opt
    from incubator_mxnet_tpu.parallel import DataParallel

    mx.random.seed(seed)
    net = gluon.nn.Dense(units, in_units=in_units)
    net.initialize()
    dp = DataParallel(net, lambda o, y: ((o - y) ** 2),
                      opt.SGD(learning_rate=0.1), mesh=mesh,
                      param_shardings=param_shardings)
    return net, dp


def test_elastic_chaos_shrink_convergence(_fast_retries):
    """ISSUE 13 acceptance gate: a seeded mid-run topology shrink
    (8 -> 4 devices at a drained step boundary) converges to the SAME
    final loss as the unfaulted run, the transition metrics are nonzero,
    and the post-shrink layout passes shardcheck clean."""
    from incubator_mxnet_tpu.fault.elastic import ElasticController
    from incubator_mxnet_tpu.parallel import dist
    from incubator_mxnet_tpu.parallel.mesh import make_mesh

    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 4)).astype("float32")
    w = rng.uniform(-1, 1, (4, 1)).astype("float32")
    Y = X @ w

    def run(chaos):
        dist._reset_membership()
        injection.clear_injection()
        net, dp = _make_dp(make_mesh({"dp": 8}))
        ctl = ElasticController(trainer=dp)
        if chaos:
            injection.configure_injection(
                "topology_change:1.0:11:1:shrink=4")
        losses = []
        for step in range(12):
            losses.append(float(dp.step(X, Y)))
            verdict = ctl.poll()            # drained step boundary
            if chaos and step == 0:
                assert verdict == "shrunk"
        injection.clear_injection()
        return losses, dp

    losses_a, _ = run(chaos=False)
    t0 = _counter("mx_elastic_transitions_total")
    losses_b, dp_b = run(chaos=True)

    # the shrink kept the global batch: the trajectory is preserved
    assert abs(losses_a[-1] - losses_b[-1]) <= 0.02, (
        losses_a[-1], losses_b[-1])
    assert int(dp_b.mesh.devices.size) == 4
    assert dist.generation() == 1
    # transition was measured
    assert _counter("mx_elastic_transitions_total") == t0 + 1
    assert _gauge("mx_elastic_reshard_seconds") > 0
    assert _gauge("mx_elastic_generation") == 1
    # post-shrink layout is shardcheck-clean (no error-severity findings)
    rep = dp_b.shardcheck_report()
    assert not [f for f in rep.findings if f.severity == "error"], (
        rep.findings)


def test_elastic_preflight_aborts_on_silent_replication():
    """A shrink that would silently replicate a large sharded param
    (its mesh axis is gone) aborts BEFORE the epoch turns, naming the
    SC001 finding."""
    import jax

    from incubator_mxnet_tpu.fault.elastic import (
        ElasticController, ElasticTransitionAborted)
    from incubator_mxnet_tpu.parallel import dist
    from incubator_mxnet_tpu.parallel.mesh import make_mesh

    P = jax.sharding.PartitionSpec
    mesh = make_mesh({"dp": 2, "tp": 4})
    # Dense(512, in_units=512): the 1 MiB weight rides 'tp', bias repl
    net, dp = _make_dp(mesh, units=512, in_units=512,
                       param_shardings=[P(None, "tp"), P()])
    ctl = ElasticController(trainer=dp)
    gen0 = dist.generation()
    new_mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])  # no 'tp'
    with pytest.raises(ElasticTransitionAborted) as ei:
        ctl._preflight(new_mesh)
    assert any(f.rule == "SC001" for f in ei.value.findings)
    assert "SC001" in str(ei.value)
    assert retry.classify_exception(ei.value) == "fatal"
    assert dist.generation() == gen0        # nothing committed


def test_elastic_resume_across_device_count(tmp_path):
    """Acceptance: save under mesh A (8 devices), resume under mesh B
    (4 devices) — the layout sidecar routes the load through
    reshard_net and the next-step loss matches the uninterrupted run."""
    import json

    from incubator_mxnet_tpu.fault import elastic
    from incubator_mxnet_tpu.parallel.mesh import make_mesh

    rng = onp.random.RandomState(3)
    X = rng.uniform(-1, 1, (64, 4)).astype("float32")
    w = rng.uniform(-1, 1, (4, 1)).astype("float32")
    Y = X @ w

    # -- run A: train 3 steps on dp=8, checkpoint, take step-4 loss --
    net_a, dp_a = _make_dp(make_mesh({"dp": 8}), seed=9)
    ck_a = preemption.TrainingCheckpointer(
        str(tmp_path / "el"), net_a, every_n=1, register_signal=False,
        layout_fn=lambda: elastic.checkpoint_layout(dp_a))
    for _ in range(3):
        dp_a.step(X, Y)
        ck_a.step()
    path = ck_a._mgr.latest()               # every_n=1: step 3 is on disk
    loss_ref = float(dp_a.step(X, Y))

    side = preemption.load_layout(path)
    assert side["format"] == 2
    assert side["mesh"] == {"axes": [["dp", 8]]}
    assert any(k.startswith("param/") for k in side["leaves"])

    # -- resume under a SHRUNK topology: fake the device-count delta the
    # sidecar would carry across real machines (same host here) --
    side["device_count"] = 999
    with open(path + preemption._LAYOUT_SUFFIX, "w") as f:
        json.dump(side, f)

    # disabled elastic = a clear LayoutMismatch, not a jax shape error
    net_b = gluon.nn.Dense(1, in_units=4)
    net_b.initialize()
    ck_b = preemption.TrainingCheckpointer(
        str(tmp_path / "el"), net_b, register_signal=False)
    with environment("MXNET_ELASTIC", "0"):
        with pytest.raises(preemption.LayoutMismatch):
            ck_b.resume()

    # enabled (default): resume reshards onto the live topology...
    r0 = _counter("mx_elastic_layout_resumes_total")
    step = ck_b.resume()
    assert step == 3
    assert _counter("mx_elastic_layout_resumes_total") == r0 + 1

    # ...and the next step on mesh B reproduces run A's step-4 loss
    import jax

    from incubator_mxnet_tpu import optimizer as opt
    from incubator_mxnet_tpu.parallel import DataParallel
    dp_b = DataParallel(net_b, lambda o, y: ((o - y) ** 2),
                        opt.SGD(learning_rate=0.1),
                        mesh=make_mesh({"dp": 4},
                                       devices=jax.devices()[:4]))
    loss_b = float(dp_b.step(X, Y))
    assert abs(loss_b - loss_ref) <= 1e-4, (loss_b, loss_ref)


def test_elastic_controller_disabled_is_noop():
    from incubator_mxnet_tpu.fault.elastic import ElasticController

    injection.configure_injection("topology_change:1.0:0:9:shrink=4")
    ctl = ElasticController()
    with environment("MXNET_ELASTIC", "0"):
        # the seam is armed but elastic is off: no transition, no raise
        assert ctl.poll() == "stable"
    assert injection.schedule_info()["topology_change"]["fired"] == 0


def test_topology_seam_grow_parse_and_classification():
    from incubator_mxnet_tpu.fault.injection import TopologyChanged

    injection.configure_injection("topology_change:1.0:3:2:grow=8")
    info = injection.schedule_info()["topology_change"]
    assert info["kind"] == "topology"
    assert info["grow"] == 8 and info["shrink"] is None
    with pytest.raises(TopologyChanged) as ei:
        injection.inject_at("topology_change")
    assert ei.value.grow == 8 and ei.value.shrink is None
    # a grow is still a membership event, not a transient fault
    assert ei.value.non_retryable
    assert retry.classify_exception(ei.value) == "fatal"
    import pickle
    e2 = pickle.loads(pickle.dumps(ei.value))
    assert isinstance(e2, TopologyChanged) and e2.grow == 8


def test_elastic_chaos_grow_roundtrip_convergence(_fast_retries):
    """ISSUE 18 acceptance gate: a seeded 8 -> 4 -> 8 round-trip
    (shrink at step 0, grow back at step 1, both at drained step
    boundaries) converges to the SAME final loss as the unfaulted run,
    lands at membership generation 2 with a readmission counted, fails
    a stale-generation collective loudly, and the goodput ledger's
    states sum to wall."""
    import jax.numpy as jnp

    from incubator_mxnet_tpu.fault.elastic import ElasticController
    from incubator_mxnet_tpu.parallel import dist
    from incubator_mxnet_tpu.parallel.mesh import make_mesh
    from incubator_mxnet_tpu.telemetry import goodput

    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 4)).astype("float32")
    w = rng.uniform(-1, 1, (4, 1)).astype("float32")
    Y = X @ w

    def run(chaos):
        dist._reset_membership()
        injection.clear_injection()
        net, dp = _make_dp(make_mesh({"dp": 8}))
        ctl = ElasticController(trainer=dp)
        losses = []
        if chaos:
            injection.configure_injection(
                "topology_change:1.0:11:1:shrink=4")
        for step in range(12):
            losses.append(float(dp.step(X, Y)))
            verdict = ctl.poll()            # drained step boundary
            if chaos and step == 0:
                assert verdict == "shrunk"
                injection.configure_injection(
                    "topology_change:1.0:7:1:grow=8")
            elif chaos and step == 1:
                assert verdict == "grown"
                injection.clear_injection()
        injection.clear_injection()
        return losses, dp

    losses_a, _ = run(chaos=False)
    r0 = _counter("mx_elastic_readmissions_total")
    goodput.enable()
    goodput.reset()
    try:
        losses_b, dp_b = run(chaos=True)
        gp = goodput.report()
    finally:
        goodput.disable()
        goodput.reset()

    # the round-trip preserved the trajectory and the full device set
    assert abs(losses_a[-1] - losses_b[-1]) <= 0.02, (
        losses_a[-1], losses_b[-1])
    assert int(dp_b.mesh.devices.size) == 8
    assert dist.generation() == 2
    assert _gauge("mx_elastic_generation") == 2
    # the grow was attributed: a readmission, an up scale event
    assert _counter("mx_elastic_readmissions_total") >= r0 + 1
    # a collective still holding generation 1 (pre-grow) fails loudly
    with pytest.raises(dist.StaleGenerationError):
        dist.allreduce(jnp.ones(2), generation=1)
    # the goodput ledger accounted the transitions: states sum to wall
    assert gp["wall_s"] > 0
    assert abs(sum(gp["states"].values()) - gp["wall_s"]) \
        <= 0.05 * gp["wall_s"] + 1e-3
    assert gp["states"]["reshard"] > 0
    # post-grow layout is shardcheck-clean
    rep = dp_b.shardcheck_report()
    assert not [f for f in rep.findings if f.severity == "error"], (
        rep.findings)


def test_elastic_sampler_exactly_once_across_shrink_then_grow():
    from incubator_mxnet_tpu.gluon.data import ElasticSampler

    # two ranks draw, the world shrinks to 1, draws more, then grows
    # back to 2 — every index appears EXACTLY once across all phases
    s0 = ElasticSampler(24, num_shards=2, index=0, shuffle=True, seed=7)
    s1 = ElasticSampler(24, num_shards=2, index=1, shuffle=True, seed=7)
    it0, it1 = iter(s0), iter(s1)
    drawn = [next(it0) for _ in range(3)] + [next(it1) for _ in range(3)]
    s0.reshard(num_shards=1, index=0)           # shrink: rank 1 departed
    it0 = iter(s0)
    drawn += [next(it0) for _ in range(4)]
    consumed = 24 - s0.remaining()              # what survivors broadcast
    s0.reshard(num_shards=2, index=0)           # grow: a rank re-admitted
    s1b = ElasticSampler(24, num_shards=2, index=1, shuffle=True, seed=7)
    s1b.reshard(num_shards=2, index=1, consumed=consumed)
    rest = list(s0) + list(s1b)
    assert sorted(drawn + rest) == list(range(24))
    assert s0.remaining() == 0 and s1b.remaining() == 0


# ---------------------------------------------------------------------------
# lint FL006
# ---------------------------------------------------------------------------

def _lint():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    return framework_lint


def test_lint_fl006_flags_silent_swallows():
    fl = _lint()
    bad = ("def f():\n"
           "    try:\n"
           "        g()\n"
           "    except Exception:\n"
           "        pass\n")
    rules = {x.rule for x in fl.lint_source(bad, "pkg/mod.py")}
    assert "FL006" in rules
    bare = bad.replace("except Exception:", "except:")
    assert "FL006" in {x.rule for x in fl.lint_source(bare, "pkg/mod.py")}


def test_lint_fl006_escapes():
    fl = _lint()
    noqa = ("def f():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:  # noqa: FL006 — teardown\n"
            "        pass\n")
    assert "FL006" not in {x.rule
                           for x in fl.lint_source(noqa, "pkg/mod.py")}
    logged = ("def f():\n"
              "    try:\n"
              "        g()\n"
              "    except Exception as e:\n"
              "        log(e)\n")
    assert "FL006" not in {x.rule
                           for x in fl.lint_source(logged, "pkg/mod.py")}
    narrow = ("def f():\n"
              "    try:\n"
              "        g()\n"
              "    except OSError:\n"
              "        pass\n")
    assert "FL006" not in {x.rule
                           for x in fl.lint_source(narrow, "pkg/mod.py")}


def test_cpp_bridge_optimizer_failfast():
    """VERDICT Weak #9 satellite: the C++ Optimizer ctor validates via
    `_cpp_train.check_optimizer` — unknown names raise at construction."""
    from incubator_mxnet_tpu._cpp_train import check_optimizer

    assert check_optimizer("SGD") == "sgd"
    with pytest.raises(ValueError, match="unknown optimizer"):
        check_optimizer("definitely_not_an_optimizer")
