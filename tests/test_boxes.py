"""Detection op family tests (reference:
`tests/python/unittest/test_contrib_operator.py` box_* cases)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np, npx

RNG = onp.random.RandomState(9)


def _np_iou(a, b):
    lt = onp.maximum(a[:, None, :2], b[None, :, :2])
    rb = onp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = onp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_a = ((a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]))[:, None]
    area_b = ((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))[None, :]
    return inter / onp.maximum(area_a + area_b - inter, 1e-12)


def test_box_iou_corner():
    a = onp.array([[0, 0, 2, 2], [1, 1, 3, 3]], "float32")
    b = onp.array([[0, 0, 2, 2], [2, 2, 4, 4]], "float32")
    out = npx.box_iou(np.array(a), np.array(b))
    onp.testing.assert_allclose(out.asnumpy(), _np_iou(a, b), rtol=1e-5)


def test_box_iou_center_format():
    a_center = onp.array([[1, 1, 2, 2]], "float32")  # == corner [0,0,2,2]
    b_corner = onp.array([[0, 0, 2, 2]], "float32")
    out = npx.box_iou(np.array(a_center),
                      np.array(onp.array([[1, 1, 2, 2]], "float32")),
                      format="center")
    assert out.asnumpy()[0, 0] == pytest.approx(1.0)
    del b_corner


def test_box_nms_suppresses_and_compacts():
    # rows: [id, score, x1, y1, x2, y2]
    data = onp.array([
        [0, 0.9, 0, 0, 2, 2],
        [0, 0.8, 0.1, 0.1, 2.1, 2.1],   # heavy overlap with row 0 → out
        [0, 0.7, 5, 5, 7, 7],           # far away → kept
        [1, 0.6, 0.2, 0.2, 2.2, 2.2],   # other class → kept (per-class nms)
    ], "float32")
    out = npx.box_nms(np.array(data), overlap_thresh=0.5, coord_start=2,
                      score_index=1, id_index=0).asnumpy()
    # reference semantics: survivors compacted to the top in score order,
    # tail rows entirely -1
    onp.testing.assert_allclose(out[:, 1], [0.9, 0.7, 0.6, -1], rtol=1e-6)
    onp.testing.assert_allclose(out[3], -onp.ones(6))
    onp.testing.assert_allclose(out[1, 2:], [5, 5, 7, 7])


def test_box_nms_force_suppress():
    data = onp.array([
        [0, 0.9, 0, 0, 2, 2],
        [1, 0.8, 0.1, 0.1, 2.1, 2.1],
    ], "float32")
    out = npx.box_nms(np.array(data), overlap_thresh=0.5, coord_start=2,
                      score_index=1, id_index=0,
                      force_suppress=True).asnumpy()
    onp.testing.assert_allclose(out[1], -onp.ones(6))


def test_box_nms_out_format_conversion():
    data = onp.array([[0.9, 1.0, 1.0, 2.0, 2.0]], "float32")  # center wh=2
    out = npx.box_nms(np.array(data), overlap_thresh=0.5, coord_start=1,
                      score_index=0, in_format="center",
                      out_format="corner").asnumpy()
    onp.testing.assert_allclose(out[0], [0.9, 0, 0, 2, 2], atol=1e-6)


def test_box_encode_decode_roundtrip():
    anchors = onp.array([[[0, 0, 2, 2], [1, 1, 4, 5]]], "float32")
    refs = onp.array([[[0.5, 0.5, 2.5, 3.0], [1, 1, 3, 3]]], "float32")
    samples = onp.ones((1, 2), "float32")
    matches = onp.array([[0, 1]], "float32")
    targets, masks = npx.box_encode(np.array(samples), np.array(matches),
                                    np.array(anchors), np.array(refs))
    assert masks.asnumpy().min() == 1.0
    decoded = npx.box_decode(targets, np.array(anchors), format="corner")
    onp.testing.assert_allclose(decoded.asnumpy(), refs, rtol=1e-4,
                                atol=1e-4)


def test_bipartite_matching_greedy():
    scores = onp.array([[0.5, 0.6, 0.9],
                        [0.8, 0.4, 0.3]], "float32")
    rows, cols = npx.bipartite_matching(np.array(scores), threshold=0.1)
    # greedy: (0,2)=0.9 first, then (1,0)=0.8
    onp.testing.assert_array_equal(rows.asnumpy(), [2, 0])
    onp.testing.assert_array_equal(cols.asnumpy(), [1, -1, 0])


def test_bipartite_matching_threshold():
    scores = onp.array([[0.9, 0.0], [0.0, 0.05]], "float32")
    rows, cols = npx.bipartite_matching(np.array(scores), threshold=0.5)
    onp.testing.assert_array_equal(rows.asnumpy(), [0, -1])
    onp.testing.assert_array_equal(cols.asnumpy(), [0, -1])


def test_roi_align_constant_image():
    # pooling any ROI over a constant image returns that constant
    img = onp.full((1, 1, 8, 8), 3.0, "float32")
    rois = onp.array([[0, 2, 2, 6, 6]], "float32")
    out = npx.roi_align(np.array(img), np.array(rois), pooled_size=(2, 2),
                        spatial_scale=1.0, sample_ratio=2)
    assert out.shape == (1, 1, 2, 2)
    onp.testing.assert_allclose(out.asnumpy(), onp.full((1, 1, 2, 2), 3.0),
                                rtol=1e-4)


def test_roi_align_gradient_flows():
    from incubator_mxnet_tpu import autograd

    img = np.array(RNG.uniform(0, 1, (1, 2, 8, 8)).astype("float32"))
    rois = np.array(onp.array([[0, 1, 1, 6, 6]], "float32"))
    img.attach_grad()
    with autograd.record():
        out = npx.roi_align(img, rois, pooled_size=(3, 3)).sum()
    out.backward()
    g = img.grad.asnumpy()
    assert onp.isfinite(g).all()
    assert onp.abs(g).sum() > 0


def test_roi_align_batch_index():
    x = onp.stack([onp.full((1, 4, 4), 1.0), onp.full((1, 4, 4), 7.0)]) \
        .astype("float32")
    rois = onp.array([[1, 0, 0, 4, 4]], "float32")  # second image
    out = npx.roi_align(np.array(x), np.array(rois), pooled_size=1)
    assert out.asnumpy().ravel()[0] == pytest.approx(7.0)


def test_slice_like():
    a = np.array(RNG.randn(4, 6).astype("float32"))
    ref = np.zeros((2, 3))
    out = npx.slice_like(a, ref)
    onp.testing.assert_array_equal(out.asnumpy(), a.asnumpy()[:2, :3])
    out2 = npx.slice_like(a, ref, axes=(1,))
    onp.testing.assert_array_equal(out2.asnumpy(), a.asnumpy()[:, :3])


def test_broadcast_like():
    a = np.ones((1, 3))
    ref = np.zeros((4, 3))
    out = npx.broadcast_like(a, ref)
    assert out.shape == (4, 3)


def test_batch_take():
    a = np.array(onp.arange(12, dtype="float32").reshape(3, 4))
    idx = np.array(onp.array([0, 2, 3], "int32"))
    out = npx.batch_take(a, idx)
    onp.testing.assert_array_equal(out.asnumpy(), [0, 6, 11])


def test_box_nms_grad_safe_under_hybridize():
    from incubator_mxnet_tpu.gluon.block import HybridBlock

    class Net(HybridBlock):
        def forward(self, x):
            return npx.box_nms(x, overlap_thresh=0.5, coord_start=2,
                               score_index=1)

    net = Net()
    net.hybridize()
    data = np.array(RNG.uniform(0, 1, (2, 5, 6)).astype("float32"))
    y0 = net(data)
    y1 = net(data)  # compiled replay
    onp.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-5)
