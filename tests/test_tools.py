"""Tools tests: parse_log, bandwidth measure (reference model: the tools/
utilities shipped alongside the framework)."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_log(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    log = tmp_path / "train.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.91\n"
        "INFO Epoch[0] Validation-accuracy=0.88\n"
        "INFO Epoch[0] Time cost=12.3\n"
        "INFO Epoch[1] Train-accuracy=0.95\n")
    data = parse_log.parse(log.read_text().splitlines(), ["accuracy"])
    assert data[0]["train-accuracy"] == 0.91
    assert data[0]["val-accuracy"] == 0.88
    assert data[0]["time"] == 12.3
    assert data[1]["train-accuracy"] == 0.95
    md = parse_log.to_markdown(data, ["accuracy"])
    assert "| epoch |" in md and "0.91" in md


def test_parse_log_metric_name_boundary(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    data = parse_log.parse(["Epoch[0] Train-accuracy=0.70",
                            "Epoch[0] Train-accuracy-top5=0.95"],
                           ["accuracy"])
    assert data[0]["train-accuracy"] == 0.70


def test_parse_log_estimator_format(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    # one LoggingHandler epoch_end line carries time + train + validation
    lines = ["[Epoch 2] Finished in 3.211s, train accuracy: 0.7712, "
             "validation accuracy: 0.7001"]
    data = parse_log.parse(lines, ["accuracy"])
    assert data[2]["train-accuracy"] == 0.7712
    assert data[2]["val-accuracy"] == 0.7001
    assert data[2]["time"] == 3.211


def test_parse_log_escapes_metric_names():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    # regex metachars in a metric name must not crash pattern building
    data = parse_log.parse(["Epoch[0] Train-top_k(5)=0.9"], ["top_k(5)"])
    assert data[0]["train-top_k(5)"] == 0.9


def test_bandwidth_measure_runs():
    sys.path.insert(0, os.path.join(REPO, "tools", "bandwidth"))
    try:
        import measure
    finally:
        sys.path.pop(0)
    bw = measure.measure(size_mb=1.0, repeat=2)
    assert bw > 0


def test_diagnose_runs():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "diagnose.py")],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    assert "Framework Info" in proc.stdout


# ---------------------------------------------------------------------------
# framework_lint FL007 — serving-loop TPU hazards (scoped to serve/)
# ---------------------------------------------------------------------------

def _lint(src, path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    return framework_lint.lint_source(src, path)


_SERVE_PATH = "incubator_mxnet_tpu/serve/engine.py"


def test_fl007_flags_undonated_jit_in_serve():
    src = ("import jax\n"
           "def build(fn):\n"
           "    return jax.jit(fn, static_argnames=('k',))\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL007"]
    assert len(hits) == 1
    assert "donate" in hits[0].message


def test_fl007_accepts_donated_jit_and_other_paths():
    donated = ("import jax\n"
               "def build(fn):\n"
               "    return jax.jit(fn, donate_argnums=(1, 2))\n")
    assert not [f for f in _lint(donated, _SERVE_PATH)
                if f.rule == "FL007"]
    by_name = ("import jax\n"
               "def build(fn):\n"
               "    return jax.jit(fn, donate_argnames=('ck', 'cv'))\n")
    assert not [f for f in _lint(by_name, _SERVE_PATH)
                if f.rule == "FL007"]
    # the rule is scoped: the same undonated jit OUTSIDE serve/ is fine
    undonated = ("import jax\n"
                 "def build(fn):\n"
                 "    return jax.jit(fn)\n")
    assert not [f for f in _lint(undonated,
                                 "incubator_mxnet_tpu/models/decoding.py")
                if f.rule == "FL007"]


def test_fl007_flags_device_branching_in_step_loop():
    src = ("def step(active, engine):\n"
           "    if active.any():\n"
           "        engine.decode()\n"
           "    while engine.mask.all():\n"
           "        engine.decode()\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL007"]
    assert len(hits) == 2
    assert all("host" in f.message for f in hits)
    # host-side control flow (ints, lens) stays clean
    clean = ("def step(self):\n"
             "    if self.n_active == 0:\n"
             "        return False\n"
             "    while self.queue:\n"
             "        self.admit()\n")
    assert not [f for f in _lint(clean, _SERVE_PATH) if f.rule == "FL007"]


def test_fl007_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    serve_dir = os.path.join(REPO, "incubator_mxnet_tpu", "serve")
    findings = [f for f in framework_lint.lint_paths([serve_dir])
                if f.rule == "FL007"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL008 — span-tracing hygiene
# ---------------------------------------------------------------------------

_ANY_PATH = "incubator_mxnet_tpu/gluon/trainer.py"


def test_fl008_flags_bare_start_span():
    src = ("from incubator_mxnet_tpu.telemetry import tracing\n"
           "t = tracing.Tracer()\n"
           "def f():\n"
           "    s = t.start_span('work')\n"
           "    return s\n")
    hits = [f for f in _lint(src, _ANY_PATH) if f.rule == "FL008"]
    assert len(hits) == 1
    assert "with" in hits[0].message


def test_fl008_accepts_with_and_open_span():
    good = ("from incubator_mxnet_tpu.telemetry import tracing\n"
            "t = tracing.Tracer()\n"
            "def f(req):\n"
            "    with t.start_span('work'):\n"
            "        pass\n"
            "    with tracing.span('other', x=1):\n"
            "        pass\n"
            "    req.span = tracing.open_span('request')\n"
            "    req.span.close()\n")
    assert not [f for f in _lint(good, _ANY_PATH) if f.rule == "FL008"]


def test_fl008_flags_span_creation_in_ops_bodies():
    src = ("from ..telemetry import tracing\n"
           "def kernel(x):\n"
           "    with tracing.span('k'):\n"
           "        return x\n")
    hits = [f for f in _lint(src, "incubator_mxnet_tpu/ops/k.py")
            if f.rule == "FL008"]
    assert len(hits) == 1
    assert "jit-traced" in hits[0].message
    # the same source OUTSIDE ops/ is fine
    assert not [f for f in _lint(src, _ANY_PATH) if f.rule == "FL008"]
    # module-level span use in ops/ (not in a function body) is not
    # kernel-reachable — same scoping as FL003/FL005
    top = ("from ..telemetry import tracing\n"
           "with tracing.span('import'):\n"
           "    pass\n")
    assert not [f for f in _lint(top, "incubator_mxnet_tpu/ops/k.py")
                if f.rule == "FL008"]


def test_fl008_ignores_unrelated_span_names():
    # .span()/.start_span-free code and foreign attrs named 'span' on
    # non-tracing receivers must not fire (only start_span is
    # unambiguous by name alone)
    src = ("def f(soup):\n"
           "    return soup.span('x')\n")
    assert not [f for f in _lint(src, _ANY_PATH) if f.rule == "FL008"]


def test_fl008_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu"),
         os.path.join(REPO, "tools"),
         os.path.join(REPO, "bench.py")]) if f.rule == "FL008"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# FL009 — paged-serving hazards (ISSUE 6: page-table gather discipline)
# ---------------------------------------------------------------------------

def test_fl009_flags_host_iteration_over_pool():
    src = ("def drain(self):\n"
           "    for page in self._pool_k:\n"
           "        self.copy_out(page)\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL009"]
    assert len(hits) == 1 and "gather" in hits[0].message
    # host page LISTS iterate freely (allocator bookkeeping)
    clean = ("def free(self, pages):\n"
             "    for p in pages:\n"
             "        self.refs[p] -= 1\n")
    assert not [f for f in _lint(clean, _SERVE_PATH) if f.rule == "FL009"]


def test_fl009_flags_dynamic_shape_take_and_scatter():
    take = ("import jax.numpy as jnp\n"
            "def view(pool, pages):\n"
            "    return jnp.take(pool, [int(p) for p in pages], axis=0)\n")
    hits = [f for f in _lint(take, _SERVE_PATH) if f.rule == "FL009"]
    assert len(hits) == 1 and "static-shape" in hits[0].message
    scatter = ("def write(pool, pages, vals):\n"
               "    return pool.at[list(pages)].set(vals)\n")
    hits = [f for f in _lint(scatter, _SERVE_PATH) if f.rule == "FL009"]
    assert len(hits) == 1
    # static-shape arrays (the page table) pass; constant literals pass
    clean = ("import jax.numpy as jnp\n"
             "def view(pool, table, vals):\n"
             "    v = jnp.take(pool, table, axis=0)\n"
             "    return pool.at[table].set(vals), v\n")
    assert not [f for f in _lint(clean, _SERVE_PATH) if f.rule == "FL009"]
    # scoped to serve/: the same code elsewhere is not the rule's business
    assert not [f for f in _lint(take, "incubator_mxnet_tpu/ops/take.py")
                if f.rule == "FL009"]


def test_fl009_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu"),
         os.path.join(REPO, "tools"),
         os.path.join(REPO, "bench.py")]) if f.rule == "FL009"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# FL010 — sharding-spec hygiene (ISSUE 8)

_PARALLEL_PATH = "incubator_mxnet_tpu/parallel/foo.py"


def test_fl010_flags_axis_not_in_any_mesh():
    src = ("from jax.sharding import PartitionSpec as P\n"
           "def f():\n"
           "    return P('dq', None)\n")
    hits = [f for f in _lint(src, _PARALLEL_PATH) if f.rule == "FL010"]
    assert len(hits) == 1
    assert "'dq'" in hits[0].message


def test_fl010_accepts_axes_drawn_from_mesh_in_scope():
    # axis universe: make_mesh dict keys, Mesh axis_names, and *axis*
    # parameter defaults all legitimize the literal
    src = ("from jax.sharding import PartitionSpec as P\n"
           "from .mesh import make_mesh\n"
           "import jax\n"
           "def f(x, data_axis='sp'):\n"
           "    mesh = make_mesh({'dp': 2, 'tp': 4})\n"
           "    m2 = jax.sharding.Mesh(x, ('host', 'local'))\n"
           "    return (P('dp', 'tp'), P(('host', 'local')),\n"
           "            P('sp'), P(data_axis), P())\n")
    assert not [f for f in _lint(src, _PARALLEL_PATH)
                if f.rule == "FL010"]


def test_fl010_flags_constraint_outside_mesh_scope():
    src = ("import jax\n"
           "from jax.sharding import PartitionSpec as P\n"
           "from .mesh import make_mesh, mesh_scope\n"
           "def f(x):\n"
           "    mesh = make_mesh({'dp': 2})\n"
           "    return jax.lax.with_sharding_constraint(x, P('dp'))\n")
    hits = [f for f in _lint(src, _PARALLEL_PATH) if f.rule == "FL010"]
    assert len(hits) == 1
    assert "mesh_scope" in hits[0].message
    # same call under the scope (incl. the conditional idiom) is fine
    ok = ("import jax, contextlib\n"
          "from jax.sharding import PartitionSpec as P\n"
          "from .mesh import make_mesh, mesh_scope\n"
          "def f(x, m):\n"
          "    mesh = make_mesh({'dp': 2})\n"
          "    with (mesh_scope(mesh) if m else contextlib.nullcontext()):\n"
          "        return jax.lax.with_sharding_constraint(x, P('dp'))\n")
    assert not [f for f in _lint(ok, _PARALLEL_PATH) if f.rule == "FL010"]


def test_fl010_scoped_to_parallel_and_serve():
    src = ("from jax.sharding import PartitionSpec as P\n"
           "def f():\n"
           "    return P('anything')\n")
    assert not [f for f in _lint(src, "incubator_mxnet_tpu/models/foo.py")
                if f.rule == "FL010"]


def test_fl010_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu"),
         os.path.join(REPO, "tools"),
         os.path.join(REPO, "bench.py")]) if f.rule == "FL010"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# FL011 — gateway/serving boundedness (ISSUE 9)
# ---------------------------------------------------------------------------

def test_fl011_flags_unbounded_queues_in_serve():
    src = ("import collections\n"
           "import queue\n"
           "pending = collections.deque()\n"
           "stream = queue.Queue()\n"
           "sq = queue.SimpleQueue()\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL011"]
    assert len(hits) == 3
    assert any("deque" in f.message for f in hits)
    assert any("Queue" in f.message for f in hits)
    assert any("SimpleQueue" in f.message for f in hits)


def test_fl011_accepts_bounded_noqa_and_other_paths():
    bounded = (
        "import collections\n"
        "import queue\n"
        "a = collections.deque(maxlen=64)\n"
        "b = collections.deque([], 64)\n"
        "c = queue.Queue(8)\n"
        "d = queue.Queue(maxsize=8)\n"
        "e = collections.deque()  # noqa: FL011 - admission-bounded\n")
    assert not [f for f in _lint(bounded, _SERVE_PATH)
                if f.rule == "FL011"]
    # the rule is scoped: the same unbounded deque OUTSIDE serve/ is fine
    outside = "import collections\nq = collections.deque()\n"
    assert not [f for f in _lint(outside,
                                 "incubator_mxnet_tpu/gluon/trainer.py")
                if f.rule == "FL011"]


def test_fl011_flags_timeoutless_blocking_waits():
    src = ("def pump(q, ev):\n"
           "    tok = q.get()\n"
           "    ev.wait()\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL011"]
    assert len(hits) == 2
    assert all("timeout" in f.message for f in hits)
    clean = ("def pump(q, ev):\n"
             "    tok = q.get(timeout=1.0)\n"
             "    ev.wait(0.5)\n"
             "    tok2 = q.get_nowait()\n")
    assert not [f for f in _lint(clean, _SERVE_PATH)
                if f.rule == "FL011"]


def test_fl011_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu"),
         os.path.join(REPO, "tools"),
         os.path.join(REPO, "bench.py")]) if f.rule == "FL011"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# run-metadata stamping (VERDICT Weak #5: stale-rerun detectability)
# ---------------------------------------------------------------------------

def test_run_metadata_stamps_sha_and_round():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as ge
    finally:
        sys.path.pop(0)
    meta = ge.run_metadata(round_id=7)
    assert meta["round"] == "7"
    assert meta["git_sha"] and " " not in meta["git_sha"]
    # env fallback, and 'unset' (never a wall clock) when absent
    old = os.environ.pop("MXNET_RUN_ROUND", None)
    try:
        os.environ["MXNET_RUN_ROUND"] = "r42"
        assert ge.run_metadata()["round"] == "r42"
        del os.environ["MXNET_RUN_ROUND"]
        assert ge.run_metadata()["round"] == "unset"
    finally:
        if old is not None:
            os.environ["MXNET_RUN_ROUND"] = old


# ---------------------------------------------------------------------------
# FL012 — compile-observatory coverage (ISSUE 10)
# ---------------------------------------------------------------------------

_OPS_PATH = "incubator_mxnet_tpu/ops/linalg.py"


def test_fl012_flags_raw_jit_outside_entry_points():
    src = ("import jax\n"
           "f = jax.jit(lambda x: x + 1)\n"
           "g = jit(lambda x: x * 2)\n")
    hits = [f for f in _lint(src, _OPS_PATH) if f.rule == "FL012"]
    assert len(hits) == 2
    assert all("ledger" in f.message for f in hits)


def test_fl012_accepts_entry_points_noqa_and_outside_tree():
    src = "import jax\nf = jax.jit(lambda x: x + 1)\n"
    # every registered observatory entry point is exempt
    for ep in ("incubator_mxnet_tpu/ndarray/ndarray.py",
               "incubator_mxnet_tpu/gluon/block.py",
               "incubator_mxnet_tpu/serve/engine.py",
               "incubator_mxnet_tpu/parallel/sharded.py",
               "incubator_mxnet_tpu/telemetry/compiles.py"):
        assert not [f for f in _lint(src, ep) if f.rule == "FL012"], ep
    # the noqa escape carries a justification
    noqa = ("import jax\n"
            "f = jax.jit(fn)  # noqa: FL012 - trace-time inner jit\n")
    assert not [f for f in _lint(noqa, _OPS_PATH) if f.rule == "FL012"]
    # scoped to the framework tree: tools/ and tests/ are not flagged
    assert not [f for f in _lint(src, "tools/bench_something.py")
                if f.rule == "FL012"]
    # ledgered_jit is the sanctioned spelling and is not a jit call
    ok = ("from incubator_mxnet_tpu.telemetry.compiles import ledgered_jit\n"
          "f = ledgered_jit(lambda x: x, family='ops.f')\n")
    assert not [f for f in _lint(ok, _OPS_PATH) if f.rule == "FL012"]


def test_fl012_mirror_matches_compiles_registry():
    """The lint's entry-point list is a mirror of
    telemetry.compiles.OBSERVATORY_ENTRY_POINTS — drift would silently
    widen or narrow the rule."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    from incubator_mxnet_tpu.telemetry import compiles

    assert tuple(framework_lint._OBSERVATORY_ENTRY_POINTS) \
        == tuple(compiles.OBSERVATORY_ENTRY_POINTS)


def test_fl012_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL012"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# FL013 — serve/ KV-pool aliasing (ISSUE 11)
# ---------------------------------------------------------------------------

def test_fl013_flags_undonated_pool_param():
    src = ("import jax\n"
           "def decode(params, pk, pv, table, tok):\n"
           "    return tok\n"
           "f = jax.jit(decode, donate_argnums=(1,))\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL013"]
    assert len(hits) == 1
    assert "`pv`" in hits[0].message and "donate" in hits[0].message


def test_fl013_flags_scan_over_pool():
    src = ("from jax import lax\n"
           "def step(c, xs):\n"
           "    return c, None\n"
           "def run(x, pk, pv):\n"
           "    out, _ = lax.scan(step, x, (pk, pv))\n"
           "    return out\n")
    hits = [f for f in _lint(src, _SERVE_PATH) if f.rule == "FL013"]
    assert len(hits) == 1
    assert "re-stacks" in hits[0].message


def test_fl013_accepts_donated_noqa_and_outside_serve():
    # fully donated pools (fp and int8 signatures) are the idiom
    ok = ("import jax\n"
          "def decode(params, pk, pv, sk, sv, table):\n"
          "    return table\n"
          "f = jax.jit(decode, donate_argnums=(1, 2, 3, 4))\n")
    assert not [f for f in _lint(ok, _SERVE_PATH) if f.rule == "FL013"]
    # the noqa escape carries a justification
    noqa = ("import jax\n"
            "def audit(pk, pv):\n"
            "    return pk\n"
            "f = jax.jit(audit)  # noqa: FL013 - read-only analysis pass\n")
    assert not [f for f in _lint(noqa, _SERVE_PATH) if f.rule == "FL013"]
    # scans whose xs carries no pool are untouched
    scan_ok = ("from jax import lax\n"
               "def run(x, layers):\n"
               "    out, _ = lax.scan(lambda c, l: (c, None), x, layers)\n"
               "    return out\n")
    assert not [f for f in _lint(scan_ok, _SERVE_PATH)
                if f.rule == "FL013"]
    # scoped to serve/: the same source outside serve/ is not flagged
    bad = ("import jax\n"
           "def decode(params, pk, pv):\n"
           "    return params\n"
           "f = jax.jit(decode, donate_argnums=(1,))\n")
    assert not [f for f in _lint(bad, _OPS_PATH) if f.rule == "FL013"]
    # non-literal donate_argnums can't be checked statically: no flag
    dyn = ("import jax\n"
           "def decode(params, pk, pv):\n"
           "    return params\n"
           "donate = (1, 2)\n"
           "f = jax.jit(decode, donate_argnums=donate)\n")
    assert not [f for f in _lint(dyn, _SERVE_PATH) if f.rule == "FL013"]


def test_fl013_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL013"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# FL014 — collective hygiene (ISSUE 12)
# ---------------------------------------------------------------------------

_PAR_PATH = "incubator_mxnet_tpu/parallel/moe.py"
_COLL_PATH = "incubator_mxnet_tpu/parallel/collectives.py"


def test_fl014_flags_raw_lax_collectives():
    # every import spelling: `from jax import lax`, `jax.lax.`, and a
    # direct prim import
    src = ("import jax\n"
           "from jax import lax\n"
           "from jax.lax import all_gather as ag\n"
           "def f(x):\n"
           "    a = lax.psum(x, 'dp')\n"
           "    b = jax.lax.ppermute(x, 'dp', [(0, 1)])\n"
           "    c = ag(x, 'dp')\n"
           "    return a + b + c\n")
    hits = [f for f in _lint(src, _PAR_PATH) if f.rule == "FL014"]
    assert len(hits) == 3
    assert all("census" in h.message for h in hits)


def test_fl014_flags_adhoc_clock_around_dist():
    src = ("import time\n"
           "from . import dist\n"
           "def sync(x):\n"
           "    t0 = time.perf_counter()\n"
           "    out = dist.allreduce(x)\n"
           "    return out, time.perf_counter() - t0\n")
    hits = [f for f in _lint(src, _PAR_PATH) if f.rule == "FL014"]
    assert len(hits) == 2
    assert "mx_collective_seconds" in hits[0].message


def test_fl014_accepts_wrappers_noqa_and_scoping():
    # collectives.py itself is the census point: raw prims allowed
    raw = ("import jax\n"
           "def all_reduce(v, axis_name):\n"
           "    return jax.lax.psum(v, axis_name)\n")
    assert not [f for f in _lint(raw, _COLL_PATH) if f.rule == "FL014"]
    # routed through the wrappers: clean
    ok = ("from . import collectives\n"
          "def f(x):\n"
          "    return collectives.all_reduce(x, 'dp')\n")
    assert not [f for f in _lint(ok, _PAR_PATH) if f.rule == "FL014"]
    # axis_index / axis_size are queries, not comms: never flagged
    q = ("from jax import lax\n"
         "def f(x):\n"
         "    return lax.axis_index('dp')\n")
    assert not [f for f in _lint(q, _PAR_PATH) if f.rule == "FL014"]
    # noqa escape with a reason
    noqa = ("from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'dp')  # noqa: FL014 - rep typing\n")
    assert not [f for f in _lint(noqa, _PAR_PATH) if f.rule == "FL014"]
    # scoped to parallel//serve/: ops/ modules are out of scope
    assert not [f for f in _lint(
        "from jax import lax\ndef f(x):\n    return lax.psum(x, 'd')\n",
        _OPS_PATH) if f.rule == "FL014"]
    # a clock in a function with no dist calls is FL014-silent
    clock = ("import time\n"
             "def f():\n"
             "    return time.perf_counter()\n")
    assert not [f for f in _lint(clock, _PAR_PATH) if f.rule == "FL014"]


def test_fl014_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL014"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# FL015 — membership-epoch guard (ISSUE 13)
# ---------------------------------------------------------------------------

_FAULT_PATH = "incubator_mxnet_tpu/fault/elastic.py"
_DIST_PATH = "incubator_mxnet_tpu/parallel/dist.py"


def test_fl015_flags_unguarded_dist_collectives():
    src = ("from ..parallel import dist\n"
           "def sync(x, gen):\n"
           "    a = dist.allreduce(x)\n"
           "    dist.barrier()\n"
           "    b = dist.broadcast(x, root=0)\n"
           "    objs = dist.exchange_objs({'r': 0})\n"
           "    return a, b, objs\n")
    hits = [f for f in _lint(src, _FAULT_PATH) if f.rule == "FL015"]
    assert len(hits) == 4
    assert all("StaleGenerationError" in h.message for h in hits)
    # parallel/ modules are in scope too
    hits = [f for f in _lint(src, _PAR_PATH) if f.rule == "FL015"]
    assert len(hits) == 4


def test_fl015_accepts_threaded_generation_noqa_and_scoping():
    # generation= threaded: clean
    ok = ("from ..parallel import dist\n"
          "def sync(x, gen):\n"
          "    dist.barrier(generation=gen)\n"
          "    return dist.allreduce(x, generation=dist.generation())\n")
    assert not [f for f in _lint(ok, _FAULT_PATH) if f.rule == "FL015"]
    # a **kwargs splat can't be seen through statically: no flag
    splat = ("from ..parallel import dist\n"
             "def sync(x, **kw):\n"
             "    return dist.allreduce(x, **kw)\n")
    assert not [f for f in _lint(splat, _FAULT_PATH) if f.rule == "FL015"]
    # noqa escape with a reason
    noqa = ("from ..parallel import dist\n"
            "def sync(x):\n"
            "    return dist.allreduce(x)  # noqa: FL015 - single-epoch\n")
    assert not [f for f in _lint(noqa, _FAULT_PATH) if f.rule == "FL015"]
    # dist.py itself (the guard's home) is exempt
    bare = ("def barrier(tag='b'):\n"
            "    pass\n"
            "def _probe():\n"
            "    return dist.barrier()\n")
    assert not [f for f in _lint(bare, _DIST_PATH) if f.rule == "FL015"]
    # out-of-scope modules (telemetry/, ops/) are untouched
    out = ("from ..parallel import dist\n"
           "def sync(x):\n"
           "    return dist.allreduce(x)\n")
    assert not [f for f in _lint(out, _OPS_PATH) if f.rule == "FL015"]


def test_fl015_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL015"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL016 — telemetry series index (ISSUE 14)
# ---------------------------------------------------------------------------

_TELE_PATH = "incubator_mxnet_tpu/telemetry/fleet.py"


def _lint_doc(src, path, telemetry_text):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    return framework_lint.lint_source(src, path,
                                      telemetry_text=telemetry_text)


def test_fl016_flags_undocumented_series():
    src = ("from . import registry\n"
           "c = registry.counter('mx_widget_total', 'widgets')\n"
           "g = registry.gauge('mx_widget_depth', 'depth')\n")
    doc = "## Series index\n\n`mx_widget_depth` — queue depth\n"
    hits = [f for f in _lint_doc(src, _TELE_PATH, doc)
            if f.rule == "FL016"]
    assert len(hits) == 1
    assert "mx_widget_total" in hits[0].message
    assert hits[0].line == 2


def test_fl016_accepts_documented_noqa_and_scoping():
    # documented: clean
    src = "registry.counter('mx_widget_total', 'w')\n"
    doc = "mx_widget_total is counted here"
    assert not [f for f in _lint_doc(src, _TELE_PATH, doc)
                if f.rule == "FL016"]
    # noqa escape on the registration line
    noqa = "registry.counter('mx_widget_total', 'w')  # noqa: FL016\n"
    assert not [f for f in _lint_doc(noqa, _TELE_PATH, "nothing")
                if f.rule == "FL016"]
    # non-mx_ series and dynamic names are out of scope
    other = ("registry.counter('t_reqs_total', 'n')\n"
             "registry.counter(name, 'n')\n")
    assert not [f for f in _lint_doc(other, _TELE_PATH, "nothing")
                if f.rule == "FL016"]
    # the registry factory itself is exempt (helpers build names there)
    reg = "registry.counter('mx_widget_total', 'w')\n"
    assert not [f for f in _lint_doc(
        reg, "incubator_mxnet_tpu/telemetry/registry.py", "nothing")
        if f.rule == "FL016"]
    # modules outside the package are out of scope
    assert not [f for f in _lint_doc(reg, "tools/bench.py", "nothing")
                if f.rule == "FL016"]
    # no TELEMETRY.md found -> the rule stays silent, never guesses
    assert not [f for f in _lint_doc(reg, _TELE_PATH, None)
                if f.rule == "FL016"]


def test_fl016_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL016"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL017 — serve/ placement-spec provenance (ISSUE 15)
# ---------------------------------------------------------------------------

_SERVE_PATH = "incubator_mxnet_tpu/serve/sharded.py"


def _lint_src(src, path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    return framework_lint.lint_source(src, path)


def test_fl017_flags_bare_spec_literals_at_placement_sites():
    src = ("import jax\n"
           "from jax.sharding import NamedSharding, PartitionSpec as P\n"
           "def place(x, mesh):\n"
           "    return jax.device_put(x, NamedSharding(mesh, P('tp')))\n"
           "def pin(x, mesh):\n"
           "    return jax.lax.with_sharding_constraint(\n"
           "        x, NamedSharding(mesh, P(None, 'tp')))\n")
    hits = [f for f in _lint_src(src, _SERVE_PATH) if f.rule == "FL017"]
    assert len(hits) == 2
    assert "ServeLayout" in hits[0].message
    assert {h.line for h in hits} == {4, 6}


def test_fl017_accepts_layout_derived_noqa_and_scoping():
    # specs flowing through a layout: clean
    good = ("import jax\n"
            "def place(x, layout, path):\n"
            "    s = layout.sharding(layout.spec_for(path))\n"
            "    return jax.device_put(x, s)\n")
    assert not [f for f in _lint_src(good, _SERVE_PATH)
                if f.rule == "FL017"]
    # noqa escape with a reason
    noqa = ("import jax\n"
            "from jax.sharding import NamedSharding as NS\n"
            "def stage(x, mesh, p):\n"
            "    return jax.device_put(x, NS(mesh, p))  "
            "# noqa: FL017 — host staging, layout-free\n")
    assert not [f for f in _lint_src(noqa, _SERVE_PATH)
                if f.rule == "FL017"]
    # keyword form is still caught
    kw = ("import jax\n"
          "from jax.sharding import PartitionSpec\n"
          "def f(x):\n"
          "    return jax.device_put(x, device=PartitionSpec('tp'))\n")
    assert [f for f in _lint_src(kw, _SERVE_PATH) if f.rule == "FL017"]
    # outside serve/ the rule is silent (parallel/ owns its own idiom)
    bad = ("import jax\n"
           "from jax.sharding import PartitionSpec\n"
           "def f(x):\n"
           "    return jax.device_put(x, PartitionSpec('tp'))\n")
    assert not [f for f in _lint_src(
        bad, "incubator_mxnet_tpu/parallel/mesh.py") if f.rule == "FL017"]


def test_fl017_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL017"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL018 — control-plane tracked-lock provenance (ISSUE 16)
# ---------------------------------------------------------------------------

def test_fl018_flags_raw_locks_in_control_plane():
    src = ("import threading\n"
           "class Engine:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.RLock()\n"
           "        self._cv = threading.Condition()\n"
           "_MOD_LOCK = threading.Lock()\n")
    for path in ("incubator_mxnet_tpu/serve/api.py",
                 "incubator_mxnet_tpu/fault/retry.py",
                 "incubator_mxnet_tpu/telemetry/stages.py"):
        hits = [f for f in _lint_src(src, path) if f.rule == "FL018"]
        assert len(hits) == 3, (path, hits)
        assert "tracked_lock" in hits[0].message
        assert {h.line for h in hits} == {4, 5, 6}


def test_fl018_accepts_tracked_noqa_registry_and_scoping():
    # tracked_lock construction: clean
    good = ("from ..telemetry.locks import tracked_lock\n"
            "class Engine:\n"
            "    def __init__(self):\n"
            "        self._lock = tracked_lock('serve.engine')\n")
    assert not [f for f in _lint_src(
        good, "incubator_mxnet_tpu/serve/api.py") if f.rule == "FL018"]
    # noqa escape with a reason
    noqa = ("import threading\n"
            "_CELLS = threading.Lock()  "
            "# noqa: FL018 - backs the tracked locks themselves\n")
    assert not [f for f in _lint_src(
        noqa, "incubator_mxnet_tpu/telemetry/registry.py")
        if f.rule == "FL018"]
    # the tracked-lock registry module is exempt (it wraps raw locks)
    raw = "import threading\n_G = threading.Lock()\n"
    assert not [f for f in _lint_src(
        raw, "incubator_mxnet_tpu/telemetry/locks.py")
        if f.rule == "FL018"]
    # outside serve//fault//telemetry/ the rule is silent
    assert not [f for f in _lint_src(
        raw, "incubator_mxnet_tpu/parallel/dist.py") if f.rule == "FL018"]


def test_fl018_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL018"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL020 — serve/ replica-set choke point (ISSUE 18)
# ---------------------------------------------------------------------------

def test_fl020_flags_replica_list_mutations_outside_choke_point():
    src = ("class Gateway:\n"
           "    def grow(self, m, rep):\n"
           "        m.replicas.append(rep)\n"
           "    def shrink(self, m):\n"
           "        m.replicas.pop()\n"
           "    def reset(self, m):\n"
           "        m.replicas = []\n"
           "    def merge(self, m, more):\n"
           "        m.replicas += more\n")
    hits = [f for f in _lint_src(
        src, "incubator_mxnet_tpu/serve/gateway.py") if f.rule == "FL020"]
    assert len(hits) == 4, hits
    assert "ReplicaSetController" in hits[0].message
    assert {h.line for h in hits} == {3, 5, 7, 9}


def test_fl020_accepts_init_noqa_choke_point_and_scoping():
    # construction-time assignment in __init__: the sanctioned exception
    good = ("class _Model:\n"
            "    def __init__(self, replicas):\n"
            "        self.replicas = replicas\n"
            "    def read(self):\n"
            "        return list(self.replicas)\n")
    assert not [f for f in _lint_src(
        good, "incubator_mxnet_tpu/serve/gateway.py")
        if f.rule == "FL020"]
    # noqa escape with a reason
    noqa = ("def retire(m, rep):\n"
            "    m.replicas.remove(rep)  "
            "# noqa: FL020 - test-only fixture teardown\n")
    assert not [f for f in _lint_src(
        noqa, "incubator_mxnet_tpu/serve/gateway.py")
        if f.rule == "FL020"]
    # the choke point itself is exempt (mutations hold the tracked lock)
    raw = "def spawn(m, rep):\n    m.replicas.append(rep)\n"
    assert not [f for f in _lint_src(
        raw, "incubator_mxnet_tpu/serve/elastic.py")
        if f.rule == "FL020"]
    # outside serve/ the rule is silent (no routers there)
    assert not [f for f in _lint_src(
        raw, "incubator_mxnet_tpu/parallel/dist.py")
        if f.rule == "FL020"]
    # a local list named `replicas` (gateway construction) is not an
    # attribute mutation and stays clean
    local = ("def build():\n"
             "    replicas = []\n"
             "    replicas.append(1)\n"
             "    return replicas\n")
    assert not [f for f in _lint_src(
        local, "incubator_mxnet_tpu/serve/gateway.py")
        if f.rule == "FL020"]


def test_fl020_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL020"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL021 — serve/ migration choke point (ISSUE 19)
# ---------------------------------------------------------------------------

def test_fl021_flags_cross_replica_pool_access():
    src = ("def steal(dst, src, pages, payload, prompt):\n"
           "    k = src.slots._pk\n"
           "    payload = src.slots.copy_pages_out(pages)\n"
           "    dst.slots.copy_pages_in(pages, payload)\n"
           "    dst.slots.allocator.alloc(3)\n"
           "    dst.slots.allocator.incref(pages)\n"
           "    src.slots.allocator.decref(pages)\n"
           "    dst.slots.prefix_cache.register(prompt, pages)\n")
    hits = [f for f in _lint_src(
        src, "incubator_mxnet_tpu/serve/gateway.py") if f.rule == "FL021"]
    assert len(hits) == 7, hits
    assert "serve/disagg.py" in hits[0].message
    assert {h.line for h in hits} == {2, 3, 4, 5, 6, 7, 8}


def test_fl021_exempts_choke_point_self_and_reads():
    raw = ("def move(dst, src, pages, payload):\n"
           "    payload = src.slots.copy_pages_out(pages)\n"
           "    dst.slots.copy_pages_in(pages, payload)\n")
    # serve/disagg.py IS the choke point
    assert not [f for f in _lint_src(
        raw, "incubator_mxnet_tpu/serve/disagg.py") if f.rule == "FL021"]
    # outside serve/ the rule is silent
    assert not [f for f in _lint_src(
        raw, "incubator_mxnet_tpu/parallel/dist.py") if f.rule == "FL021"]
    # an engine touching ITS OWN pool is the normal serving path
    own = ("class SlotDecoder:\n"
           "    def _gather(self, pages):\n"
           "        k = self.slots._pk\n"
           "        self.slots.allocator.decref(pages)\n")
    assert not [f for f in _lint_src(
        own, "incubator_mxnet_tpu/serve/gateway.py") if f.rule == "FL021"]
    # read-only probes + lifecycle calls stay clean (gateway shutdown,
    # elastic release, capacity accounting all use these)
    reads = ("def probe(rep):\n"
             "    n = rep.slots.allocator.free_pages\n"
             "    m = rep.slots.allocator.usable_pages\n"
             "    rep.slots.prefix_cache.clear()\n"
             "    rep.slots.prefix_cache.evict_unused(4)\n"
             "    w = rep.slots.prefix_cache.shared_tokens([1])\n"
             "    rep.slots.release()\n")
    assert not [f for f in _lint_src(
        reads, "incubator_mxnet_tpu/serve/elastic.py") if f.rule == "FL021"]
    # noqa escape with a reason
    noqa = ("def fixture(rep, pages):\n"
            "    rep.slots.allocator.decref(pages)  "
            "# noqa: FL021 - test fixture teardown\n")
    assert not [f for f in _lint_src(
        noqa, "incubator_mxnet_tpu/serve/gateway.py") if f.rule == "FL021"]


def test_fl021_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL021"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# framework_lint FL022 — serve/ duration-accounting choke point (ISSUE 20)
# ---------------------------------------------------------------------------

def test_fl022_flags_adhoc_perf_counter_durations():
    # a direct subtraction outside any charge call
    direct = ("import time\n"
              "def step(self):\n"
              "    t0 = time.perf_counter()\n"
              "    work()\n"
              "    dur = time.perf_counter() - t0\n")
    hits = [f for f in _lint_src(
        direct, "incubator_mxnet_tpu/serve/scheduler.py")
        if f.rule == "FL022"]
    assert len(hits) == 1 and hits[0].line == 5, hits
    assert "charge call" in hits[0].message
    # an assigned duration that never feeds a charge call
    stray = ("import time\n"
             "def step(self, t0):\n"
             "    dt = time.perf_counter() - t0\n"
             "    self.stats.append(dt)\n")
    hits = [f for f in _lint_src(
        stray, "incubator_mxnet_tpu/serve/gateway.py")
        if f.rule == "FL022"]
    assert len(hits) == 1 and hits[0].line == 3, hits


def test_fl022_exempts_charge_fed_durations():
    # the sanctioned shape: the subtraction is an argument of the
    # capacity/anatomy charge call itself
    inline = ("import time\n"
              "def step(self, t0):\n"
              "    capacity.split_device_seconds(\n"
              "        ('t',), 'm', 'decode',\n"
              "        time.perf_counter() - t0)\n"
              "    anatomy.on_decode_step(self, t0,\n"
              "                           time.perf_counter())\n")
    assert not [f for f in _lint_src(
        inline, "incubator_mxnet_tpu/serve/scheduler.py")
        if f.rule == "FL022"]
    # an assigned dt whose name feeds a charge call is sanctioned too
    fed = ("import time\n"
           "def accrue(self, req, last):\n"
           "    t = time.perf_counter()\n"
           "    dt = t - last\n"
           "    capacity.charge_kv_page_seconds(\n"
           "        req.tenant, self.model, len(req.pages) * dt)\n")
    assert not [f for f in _lint_src(
        fed, "incubator_mxnet_tpu/serve/scheduler.py")
        if f.rule == "FL022"]
    # the choke points themselves own the subtraction
    own = ("import time\n"
           "def _transition(self, t0):\n"
           "    dur = time.perf_counter() - t0\n")
    assert not [f for f in _lint_src(
        own, "incubator_mxnet_tpu/telemetry/anatomy.py")
        if f.rule == "FL022"]
    assert not [f for f in _lint_src(
        own, "incubator_mxnet_tpu/telemetry/capacity.py")
        if f.rule == "FL022"]
    # outside serve/ the rule is silent
    assert not [f for f in _lint_src(
        own, "incubator_mxnet_tpu/parallel/dist.py")
        if f.rule == "FL022"]
    # noqa escape with a reason
    noqa = ("import time\n"
            "def step(self, t0):\n"
            "    dur = time.perf_counter() - t0  "
            "# noqa: FL022 - bench-only probe\n")
    assert not [f for f in _lint_src(
        noqa, "incubator_mxnet_tpu/serve/scheduler.py")
        if f.rule == "FL022"]


def test_fl022_tree_is_clean():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    findings = [f for f in framework_lint.lint_paths(
        [os.path.join(REPO, "incubator_mxnet_tpu")])
        if f.rule == "FL022"]
    assert not findings, findings


# ---------------------------------------------------------------------------
# bench_regress — trajectory regression gate (ISSUE 10)
# ---------------------------------------------------------------------------

def _bench_regress():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_regress
    finally:
        sys.path.pop(0)
    return bench_regress


def test_bench_regress_green_on_committed_history(capsys):
    br = _bench_regress()
    assert br.main([]) == 0
    out = capsys.readouterr().out
    # the latest committed round's headline metric must be in the table
    latest = sorted(br.glob.glob(os.path.join(REPO, "BENCH_r*.json")))[-1]
    with open(latest, encoding="utf-8") as f:
        headline = json.load(f)["parsed"]["metric"]
    assert "clean" in out and headline in out


def test_bench_regress_catches_both_polarities(tmp_path):
    br = _bench_regress()
    a = tmp_path / "BENCH_r01.json"
    b = tmp_path / "BENCH_r02.json"
    a.write_text(json.dumps({"n": 1, "parsed": {
        "metric": "tput_img_s", "value": 1000.0,
        "extras": {"step_latency_ms": 2.0, "mfu": 0.5}}}))
    # throughput -20% AND latency +50%: both directions must gate
    b.write_text(json.dumps({"n": 2, "parsed": {
        "metric": "tput_img_s", "value": 800.0,
        "extras": {"step_latency_ms": 3.0, "mfu": 0.5}}}))
    assert br.main(["--root", str(tmp_path)]) == 1
    rows = br.compare(br.flatten(json.loads(a.read_text())["parsed"]),
                      br.flatten(json.loads(b.read_text())["parsed"]))
    status = {r["metric"]: r["status"] for r in rows}
    assert status["tput_img_s"] == "REGRESS"
    assert status["step_latency_ms"] == "REGRESS"
    assert status["mfu"] == "ok"
    # within threshold is clean
    b.write_text(json.dumps({"n": 2, "parsed": {
        "metric": "tput_img_s", "value": 950.0,
        "extras": {"step_latency_ms": 2.1, "mfu": 0.51}}}))
    assert br.main(["--root", str(tmp_path)]) == 0


def test_bench_regress_family_drift_normalization(tmp_path):
    """Fleet-wide runner drift on a serving family is tolerated, but a
    single member regressing beyond the family's median delta still
    gates (the identical-code control case from the module docstring)."""
    br = _bench_regress()
    base = {"gpt_serve_ttft_p50_ms": 100.0,
            "gpt_serve_ttft_p99_ms": 300.0,
            "gpt_serve_longprompt_ttft_p99_ms": 400.0,
            "gpt_gateway_high_ttft_p99_ms": 60.0,
            "gpt_gateway_low_ttft_p99_ms": 350.0}
    # whole family +30% (slower runner): every member tracks the median
    drifted = {k: v * 1.30 for k, v in base.items()}
    rows = br.compare(base, drifted)
    status = {r["metric"]: r["status"] for r in rows}
    assert all(s == "ok" for s in status.values()), status
    assert all(r["drift_pct"] is not None for r in rows)
    # same drift, but ONE member blows 60% past it: that member gates
    drifted["gpt_serve_ttft_p99_ms"] = base["gpt_serve_ttft_p99_ms"] * 1.90
    rows = br.compare(base, drifted)
    status = {r["metric"]: r["status"] for r in rows}
    assert status["gpt_serve_ttft_p99_ms"] == "REGRESS"
    assert status["gpt_serve_ttft_p50_ms"] == "ok"
    # below MIN_FAMILY members the estimate is untrusted: absolute gate
    small = {k: base[k] for k in list(base)[:2]}
    rows = br.compare(small, {k: v * 1.30 for k, v in small.items()})
    assert {r["status"] for r in rows} == {"REGRESS"}
    # skip-listed gateway p50s inform the median but are never gated
    assert br.re.compile(br.DEFAULT_SKIP).search(
        "gpt_gateway_high_ttft_p50_ms")


def test_bench_regress_direction_and_edge_cases(tmp_path):
    br = _bench_regress()
    # direction heuristic: _ms/latency lower-better, _vs_ report-only
    assert br.direction("decode_latency_us") == "lower"
    assert br.direction("dot_framework_ms") == "lower"
    assert br.direction("bert_base_train_tokens_s") == "higher"
    assert br.direction("resnet50_int8_vs_fp32_wall") is None
    assert br.direction("gpt_serve_tracing_overhead_pct") is None
    assert br.direction("collective_wrapper_overhead_pct") is None
    assert br.direction("vs_baseline") == "higher"
    # <2 rounds: nothing to compare, clean exit
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "parsed": {"metric": "m", "value": 1.0}}))
    assert br.main(["--root", str(tmp_path)]) == 0
    # empty dir: usage error
    empty = tmp_path / "empty"
    empty.mkdir()
    assert br.main(["--root", str(empty)]) == 2
