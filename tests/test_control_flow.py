"""Control flow ops: eager Python path and lax lowering under hybridize
(reference: `tests/python/unittest/test_contrib_control_flow.py`)."""
import numpy as onp

from incubator_mxnet_tpu import np, npx
from incubator_mxnet_tpu.gluon.block import HybridBlock

RNG = onp.random.RandomState(7)


def _body(xi, states):
    s = states[0]
    return xi + s, [s + xi.sum()]


def test_foreach_eager():
    x = np.array(RNG.randn(4, 3).astype("float32"))
    outs, states = npx.foreach(_body, x, [np.zeros(())])
    acc = 0.0
    expect = []
    xn = x.asnumpy()
    for i in range(4):
        expect.append(xn[i] + acc)
        acc += xn[i].sum()
    onp.testing.assert_allclose(outs.asnumpy(), onp.stack(expect),
                                rtol=1e-5, atol=1e-6)
    assert float(states[0].item()) == onp.float32(acc)


def test_foreach_lowers_to_scan():
    class Net(HybridBlock):
        def forward(self, x):
            outs, st = npx.foreach(_body, x, [np.zeros(())])
            return outs + st[0]

    net = Net()
    net.hybridize()
    x = np.array(RNG.randn(5, 2).astype("float32"))
    net(x)          # eager warmup
    y_compiled = net(x)  # compiled replay
    outs_e, st_e = npx.foreach(_body, x, [np.zeros(())])
    onp.testing.assert_allclose(y_compiled.asnumpy(),
                                (outs_e + st_e[0]).asnumpy(),
                                rtol=1e-5, atol=1e-5)


def test_foreach_multi_data():
    a = np.array(RNG.randn(3, 2).astype("float32"))
    b = np.array(RNG.randn(3, 2).astype("float32"))

    def body(xs, states):
        return xs[0] * xs[1], states

    outs, _ = npx.foreach(body, [a, b], [np.zeros(())])
    onp.testing.assert_allclose(outs.asnumpy(), a.asnumpy() * b.asnumpy(),
                                rtol=1e-5)


def test_while_loop_eager():
    def cond_fn(i, total):
        return i < 4

    def body_fn(i, total):
        return total, (i + 1, total + i)

    outs, (i, total) = npx.while_loop(
        cond_fn, body_fn,
        (np.zeros((), dtype="int32"), np.zeros((), dtype="int32")))
    assert int(i.item()) == 4
    assert int(total.item()) == 0 + 1 + 2 + 3
    onp.testing.assert_array_equal(outs.asnumpy(), [0, 0, 1, 3])


def test_while_loop_lowers():
    class Net(HybridBlock):
        def forward(self, x):
            def body_fn(i, acc):
                return acc, (i + 1, acc + x.sum())

            outs, (i, acc) = npx.while_loop(
                lambda i, acc: i < 5, body_fn,
                (np.zeros((), dtype="int32"), np.zeros(())),
                max_iterations=8)
            return acc

        infer_shape = None

    net = Net()
    net.hybridize()
    x = np.ones((2, 2))
    net(x)
    y = net(x)
    assert float(y.asnumpy()) == 5 * 4.0


def test_cond_eager():
    x = np.ones((2,))
    out = npx.cond(np.array(1.0), lambda: x * 3, lambda: x)
    onp.testing.assert_array_equal(out.asnumpy(), [3, 3])
    out = npx.cond(np.array(0.0), lambda: x * 3, lambda: x)
    onp.testing.assert_array_equal(out.asnumpy(), [1, 1])


def test_cond_lowers_both_branches():
    class Net(HybridBlock):
        def forward(self, x):
            return npx.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)

    net = Net()
    net.hybridize()
    xp = np.ones((2, 2))
    xn = np.ones((2, 2)) * -1
    net(xp)  # warmup
    onp.testing.assert_allclose(net(xp).asnumpy(), 2 * onp.ones((2, 2)))
    # same compiled program must take the else branch on negative input
    onp.testing.assert_allclose(net(xn).asnumpy(), -2 * onp.ones((2, 2)))


def test_foreach_lowers_to_lax_scan_under_trace():
    """The traced-lowering claim, pinned structurally: a jitted foreach
    must contain ONE `scan` equation (not T unrolled body copies)."""
    import jax

    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    def f(xs, s0):
        def body(x, st):
            return x * 2 + st, st + x.sum()
        out, st = npx.foreach(body, NDArray(xs), NDArray(s0))
        return out._data, st._data

    xs = onp.ones((16, 3), "float32")
    s0 = onp.zeros((3,), "float32")
    jaxpr = jax.make_jaxpr(f)(xs, s0)
    prims = [e.primitive.name for e in jaxpr.jaxpr.eqns]
    assert prims.count("scan") == 1, prims
    # and no unrolled arithmetic: far fewer eqns than sequence length
    assert len(prims) < 10, prims


def test_while_loop_lowers_to_lax_while_under_trace():
    import jax

    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    def f(x):
        outs, states = npx.while_loop(
            cond=lambda st: (st.sum() < 100.0),
            func=lambda st: (st, [st * NDArray(onp.float32(1.5))]),
            loop_vars=[NDArray(x)], max_iterations=50)
        return states[0]._data

    jaxpr = jax.make_jaxpr(f)(onp.ones((3,), "float32"))
    prims = [e.primitive.name for e in jaxpr.jaxpr.eqns]
    assert "while" in prims or "scan" in prims, prims
