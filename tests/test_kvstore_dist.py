"""Multi-process distributed kvstore test (reference:
`tests/nightly/dist_sync_kvstore.py` run via `tools/launch.py --launcher
local` — asserts EXACT aggregated values across worker processes).

Here: tools/launch.py forks 2 CPU processes that join jax.distributed and
allreduce through KVStoreDist; each asserts the exact cross-process sums.
"""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as onp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import np

    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    assert n == 2, n

    # pushpull: exact sum of per-rank values
    g = np.full((4,), float(rank + 1))
    out = np.zeros((4,))
    kv.pushpull("grad", g, out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.full(4, 3.0))

    # init broadcasts rank 0's value
    init_val = np.full((3,), 7.0) if rank == 0 else np.full((3,), -1.0)
    kv.init("w", init_val)
    pulled = np.zeros((3,))
    kv.pull("w", out=pulled)
    onp.testing.assert_allclose(pulled.asnumpy(), onp.full(3, 7.0))

    # push applies the cross-process-summed gradient through the updater
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push("w", np.full((3,), float(rank + 1)))  # summed grad = 3
    kv.pull("w", out=pulled)
    onp.testing.assert_allclose(pulled.asnumpy(), onp.full(3, 7.0 - 0.3),
                                rtol=1e-6)
    # server-profiler command channel (reference:
    # KVStoreServerProfilerCommand kSetConfig/kState): rank 0 issues
    # 'server' commands; the next sync point ships them to EVERY process
    from incubator_mxnet_tpu import profiler
    if rank == 0:
        profiler.set_config(filename=f"remote_prof.json",
                            profile_process="server")
        profiler.set_state("run", profile_process="server")
    kv.barrier()                         # command channel rides the sync
    assert profiler.is_running(), f"rank {rank}: server 'run' not applied"
    assert profiler._CONFIG["filename"] == "remote_prof.json", rank
    if rank == 0:
        profiler.set_state("stop", profile_process="server")
    kv.barrier()
    assert not profiler.is_running(), f"rank {rank}: 'stop' not applied"

    kv.barrier()
    print(f"worker {rank} ok", flush=True)
""")


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_dist_sync_kvstore_two_processes(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    # children must NOT inherit the parent's forced 8-device flag config
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--port", str(_free_port()), sys.executable, str(script)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=280)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "worker 0 ok" in res.stdout
    assert "worker 1 ok" in res.stdout
