"""Reference binary .params container (ndarray/legacy_io.py byte-format
reimplementation of `src/ndarray/ndarray.cc:1862-2155`)."""
import struct

import numpy as onp
import pytest

from incubator_mxnet_tpu import nd, np
from incubator_mxnet_tpu.ndarray import legacy_io
from incubator_mxnet_tpu.ndarray.sparse import (CSRNDArray, RowSparseNDArray,
                                                csr_matrix)


def test_dense_roundtrip(tmp_path):
    f = str(tmp_path / "m.params")
    data = {
        "w": np.array(onp.arange(6, dtype="float32").reshape(2, 3)),
        "b16": np.array(onp.ones((2, 2), dtype="float16")),
        "i64": np.array(onp.arange(4, dtype="int64")),
    }
    legacy_io.save(f, data)
    back = legacy_io.load(f)
    assert set(back) == set(data)
    for k in data:
        onp.testing.assert_array_equal(back[k].asnumpy(), data[k].asnumpy())
        assert back[k].asnumpy().dtype == data[k].asnumpy().dtype


def test_list_roundtrip_unnamed(tmp_path):
    f = str(tmp_path / "l.params")
    legacy_io.save(f, [np.ones((2,)), np.zeros((3, 1))])
    back = legacy_io.load(f)
    assert isinstance(back, list) and len(back) == 2
    onp.testing.assert_array_equal(back[0].asnumpy(), onp.ones((2,)))


def test_sparse_roundtrip(tmp_path):
    f = str(tmp_path / "s.params")
    rs = RowSparseNDArray(onp.arange(6, dtype="float32").reshape(2, 3),
                          onp.array([1, 4], onp.int32), (6, 3))
    csr = csr_matrix(onp.array([[0, 1.5, 0], [2.0, 0, 0]], onp.float32))
    legacy_io.save(f, {"rs": rs, "csr": csr})
    back = legacy_io.load(f)
    assert isinstance(back["rs"], RowSparseNDArray)
    assert isinstance(back["csr"], CSRNDArray)
    onp.testing.assert_array_equal(back["rs"].asnumpy(), rs.asnumpy())
    onp.testing.assert_array_equal(back["csr"].asnumpy(), csr.asnumpy())


def test_wire_framing(tmp_path):
    """The emitted bytes follow the reference framing exactly: 0x112 magic,
    reserved, uint64 count, per-array V3 magic + stype + shape..."""
    f = str(tmp_path / "w.params")
    legacy_io.save(f, {"x": np.ones((2, 3), dtype="float32")})
    raw = open(f, "rb").read()
    magic, reserved, count = struct.unpack_from("<QQQ", raw, 0)
    assert magic == 0x112 and reserved == 0 and count == 1
    blob_magic, stype, ndim = struct.unpack_from("<IiI", raw, 24)
    assert blob_magic == 0xF993FACA  # V3 np-shape
    assert stype == 0
    assert ndim == 2
    d0, d1 = struct.unpack_from("<qq", raw, 36)
    assert (d0, d1) == (2, 3)
    dev_type, dev_id, type_flag = struct.unpack_from("<iii", raw, 52)
    assert dev_type == 1 and type_flag == 0  # cpu, float32
    payload = struct.unpack_from("<6f", raw, 64)
    assert payload == (1.0,) * 6
    # names vector: uint64 count, uint64 len, bytes
    name_off = 64 + 24
    n_names, = struct.unpack_from("<Q", raw, name_off)
    assert n_names == 1
    ln, = struct.unpack_from("<Q", raw, name_off + 8)
    assert raw[name_off + 16:name_off + 16 + ln] == b"x"


def test_nd_save_load_legacy_autodetect(tmp_path):
    f = str(tmp_path / "auto.params")
    nd.save(f, {"x": np.full((2, 2), 7.0)}, format="legacy")
    back = nd.load(f)
    onp.testing.assert_array_equal(back["x"].asnumpy(),
                                   onp.full((2, 2), 7.0))


def test_block_load_parameters_legacy(tmp_path):
    from incubator_mxnet_tpu import gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize()
    x = np.random.uniform(size=(1, 3))
    y0 = net(x)
    # write a reference-style .params with arg: prefixes
    f = str(tmp_path / "ref.params")
    legacy_io.save(f, {"arg:" + k: p.data()
                       for k, p in net.collect_params().items()})
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(4))
    net2.load_parameters(f)
    onp.testing.assert_allclose(net2(x).asnumpy(), y0.asnumpy(),
                                rtol=1e-6)


def test_bad_magic_raises(tmp_path):
    f = str(tmp_path / "junk.params")
    with open(f, "wb") as fh:
        fh.write(b"\x00" * 32)
    with pytest.raises(ValueError, match="not a reference NDArray file"):
        legacy_io.load(f)
    assert not legacy_io.is_legacy_file(f)
