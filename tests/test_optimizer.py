"""Optimizer tests (modeled on tests/python/unittest/test_optimizer.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, optimizer as opt
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.test_utils import assert_almost_equal

ALL_OPTS = ["sgd", "nag", "adam", "adamw", "adabelief", "adadelta", "adagrad",
            "adamax", "dcasgd", "ftml", "ftrl", "lamb", "lans", "lars",
            "nadam", "rmsprop", "sgld", "signum"]


@pytest.mark.parametrize("name", ALL_OPTS)
def test_optimizer_decreases_quadratic(name):
    """Every optimizer must make progress on f(w) = ||w - w*||^2."""
    mx.random.seed(0)
    target = onp.array([1.0, -2.0, 3.0], dtype="float32")
    w = NDArray(onp.zeros(3, dtype="float32"))
    o = opt.create(name)
    state = o.create_state(0, w)
    f0 = float(((w.asnumpy() - target) ** 2).sum())
    for _ in range(200):
        g = NDArray(2 * (w.asnumpy() - target))
        o.update(0, w, g, state)
    f1 = float(((w.asnumpy() - target) ** 2).sum())
    assert f1 < f0, f"{name}: {f0} -> {f1}"


def test_sgd_momentum_math():
    w = NDArray(onp.array([1.0], dtype="float32"))
    g = NDArray(onp.array([0.5], dtype="float32"))
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    # mom = -lr*g = -0.05 ; w = 1 - 0.05
    assert_almost_equal(w.asnumpy(), onp.array([0.95]), rtol=1e-6)
    o.update(0, w, g, state)
    # mom = 0.9*(-0.05) - 0.05 = -0.095 ; w = 0.95 - 0.095
    assert_almost_equal(w.asnumpy(), onp.array([0.855]), rtol=1e-6)


def test_adam_first_step_is_lr():
    w = NDArray(onp.array([0.0], dtype="float32"))
    g = NDArray(onp.array([10.0], dtype="float32"))
    o = opt.Adam(learning_rate=0.001)
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    # adam's first step magnitude ≈ lr regardless of grad scale
    assert abs(abs(float(w.asnumpy()[0])) - 0.001) < 1e-4


def test_rescale_and_clip():
    w = NDArray(onp.array([0.0], dtype="float32"))
    g = NDArray(onp.array([100.0], dtype="float32"))
    o = opt.SGD(learning_rate=1.0, rescale_grad=0.01)
    o.update(0, w, g, o.create_state(0, w))
    assert_almost_equal(w.asnumpy(), onp.array([-1.0]), rtol=1e-6)
    w2 = NDArray(onp.array([0.0], dtype="float32"))
    o2 = opt.SGD(learning_rate=1.0, clip_gradient=0.1)
    o2.update(0, w2, NDArray(onp.array([100.0], dtype="float32")),
              o2.create_state(0, w2))
    assert_almost_equal(w2.asnumpy(), onp.array([-0.1]), rtol=1e-6)


def test_weight_decay():
    w = NDArray(onp.array([1.0], dtype="float32"))
    g = NDArray(onp.array([0.0], dtype="float32"))
    o = opt.SGD(learning_rate=0.1, wd=0.1)
    o.update(0, w, g, o.create_state(0, w))
    assert_almost_equal(w.asnumpy(), onp.array([0.99]), rtol=1e-6)


def test_lr_scheduler():
    sched = opt.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    o = opt.SGD(lr_scheduler=sched, learning_rate=1.0)
    assert o.learning_rate == 1.0
    o.num_update = 15
    assert o.learning_rate == 0.5
    cos = opt.CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0)
    assert abs(cos(0) - 1.0) < 1e-6
    assert abs(cos(100)) < 1e-6
    assert 0.4 < cos(50) < 0.6
    multi = opt.MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert abs(multi(1) - 1.0) < 1e-9
    assert abs(multi(7) - 0.1) < 1e-9
    assert abs(multi(12) - 0.01) < 1e-9


def test_updater_states_roundtrip():
    o = opt.Adam()
    u = opt.get_updater(o)
    w = NDArray(onp.ones(4, dtype="float32"))
    g = NDArray(onp.full(4, 0.1, dtype="float32"))
    u(0, g, w)
    u(0, g, w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.Adam())
    u2.set_states(blob)
    assert 0 in u2.states


def test_trainer_save_load_states(tmp_path):
    from incubator_mxnet_tpu import autograd, gluon

    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    X = np.ones((4, 3))
    with autograd.record():
        loss = net(X).sum()
    loss.backward()
    tr.step(4)
    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)
    tr2 = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    tr2.load_states(fname)
    assert tr2._states_initialized[0]
