"""Loss and metric depth: closed-form values on tiny inputs, weighting
and batch-axis semantics, metric update/reset cycles (reference:
`tests/python/unittest/test_loss.py`, `test_metric.py`)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np
from incubator_mxnet_tpu.gluon import loss as gloss
from incubator_mxnet_tpu.gluon import metric as gmetric

RNG = onp.random.RandomState(31)


def _a(*shape):
    return onp.array(RNG.uniform(-1, 1, shape), "float32")


# -- losses ------------------------------------------------------------------

def test_l2_loss_value():
    p, y = _a(4, 3), _a(4, 3)
    got = gloss.L2Loss()(np.array(p), np.array(y)).asnumpy()
    onp.testing.assert_allclose(got, ((p - y) ** 2).mean(axis=1) / 2,
                                rtol=1e-5)


def test_l1_loss_value():
    p, y = _a(4, 3), _a(4, 3)
    got = gloss.L1Loss()(np.array(p), np.array(y)).asnumpy()
    onp.testing.assert_allclose(got, onp.abs(p - y).mean(axis=1),
                                rtol=1e-5)


def test_softmax_ce_sparse_value():
    logits = onp.array([[2.0, 1.0, 0.0]], "float32")
    got = float(gloss.SoftmaxCrossEntropyLoss()(
        np.array(logits), np.array(onp.array([0.0], "float32"))).asnumpy())
    ref = -onp.log(onp.exp(2.0) / onp.exp([2.0, 1.0, 0.0]).sum())
    assert got == pytest.approx(float(ref), rel=1e-5)


def test_softmax_ce_dense_label():
    logits = _a(2, 4)
    dense = onp.array([[0.25, 0.25, 0.25, 0.25],
                       [1.0, 0.0, 0.0, 0.0]], "float32")
    l = gloss.SoftmaxCrossEntropyLoss(sparse_label=False)
    got = l(np.array(logits), np.array(dense)).asnumpy()
    logp = onp.log(onp.exp(logits) / onp.exp(logits).sum(-1, keepdims=True))
    onp.testing.assert_allclose(got, -(logp * dense).sum(-1), rtol=1e-4)


def test_sigmoid_bce_from_logits_stable():
    x = onp.array([[100.0, -100.0]], "float32")
    y = onp.array([[1.0, 0.0]], "float32")
    got = gloss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)(
        np.array(x), np.array(y)).asnumpy()
    onp.testing.assert_allclose(got, 0.0, atol=1e-5)  # no overflow


def test_kl_div_value():
    logp = onp.log(onp.array([[0.5, 0.5]], "float32"))
    q = onp.array([[0.9, 0.1]], "float32")
    got = float(gloss.KLDivLoss(from_logits=True)(
        np.array(logp), np.array(q)).asnumpy())
    ref = (q * (onp.log(q) - logp)).sum() / 2   # batch-mean over axis 1
    assert got == pytest.approx(float(ref), rel=1e-4)


def test_huber_switches_at_rho():
    l = gloss.HuberLoss(rho=1.0)
    p = onp.array([[0.5], [3.0]], "float32")
    y = onp.zeros((2, 1), "float32")
    got = l(np.array(p), np.array(y)).asnumpy()
    onp.testing.assert_allclose(got[0], 0.5 * 0.25, rtol=1e-5)  # quadratic
    onp.testing.assert_allclose(got[1], 3.0 - 0.5, rtol=1e-5)   # linear


def test_hinge_loss_value():
    l = gloss.HingeLoss()
    p = onp.array([[0.5], [2.0]], "float32")
    y = onp.array([[1.0], [-1.0]], "float32")
    got = l(np.array(p), np.array(y)).asnumpy()
    onp.testing.assert_allclose(got.reshape(-1), [0.5, 3.0], rtol=1e-5)


def test_triplet_loss_margin():
    l = gloss.TripletLoss(margin=1.0)
    a = onp.zeros((1, 2), "float32")
    pos = onp.zeros((1, 2), "float32")
    neg = onp.full((1, 2), 2.0, "float32")
    got = float(l(np.array(a), np.array(pos), np.array(neg)).asnumpy())
    assert got == pytest.approx(0.0)       # clamped: neg far enough


def test_cosine_embedding_loss():
    l = gloss.CosineEmbeddingLoss()
    a = onp.array([[1.0, 0.0]], "float32")
    b = onp.array([[1.0, 0.0]], "float32")
    got = float(l(np.array(a), np.array(b),
                  np.array(onp.array([1.0], "float32"))).asnumpy())
    assert got == pytest.approx(0.0, abs=1e-5)


def test_sample_weight_scales_loss():
    p, y = _a(4, 3), _a(4, 3)
    base = gloss.L2Loss()(np.array(p), np.array(y)).asnumpy()
    w = onp.array([1.0, 0.0, 2.0, 1.0], "float32").reshape(4, 1)
    got = gloss.L2Loss()(np.array(p), np.array(y),
                         np.array(w)).asnumpy()
    onp.testing.assert_allclose(got, base * w[:, 0], rtol=1e-5)


def test_loss_weight_constructor():
    p, y = _a(3, 2), _a(3, 2)
    base = gloss.L2Loss()(np.array(p), np.array(y)).asnumpy()
    got = gloss.L2Loss(weight=3.0)(np.array(p), np.array(y)).asnumpy()
    onp.testing.assert_allclose(got, base * 3.0, rtol=1e-5)


def test_ctc_loss_runs_and_is_positive():
    N, T, C = 2, 8, 5                      # default layout NTC
    logits = np.array(_a(N, T, C))
    labels = np.array(onp.array([[1, 2], [3, 4]], "float32"))
    got = gloss.CTCLoss()(logits, labels).asnumpy()
    assert got.shape == (N,)
    assert (got > 0).all()


def test_loss_grad_flows():
    p = np.array(_a(4, 3))
    p.attach_grad()
    y = np.array(_a(4, 3))
    with autograd.record():
        out = gloss.L2Loss()(p, y).sum()
    out.backward()
    onp.testing.assert_allclose(p.grad.asnumpy(),
                                (p.asnumpy() - y.asnumpy()) / 3,
                                rtol=1e-4)


# -- metrics -----------------------------------------------------------------

def test_accuracy_metric():
    m = gmetric.Accuracy()
    pred = np.array(onp.array([[0.1, 0.9], [0.8, 0.2]], "float32"))
    lab = np.array(onp.array([1, 1], "float32"))
    m.update(lab, pred)
    assert m.get()[1] == pytest.approx(0.5)


def test_accuracy_accumulates_and_resets():
    m = gmetric.Accuracy()
    pred = np.array(onp.array([[0.9, 0.1]], "float32"))
    m.update(np.array(onp.array([0.0], "float32")), pred)
    m.update(np.array(onp.array([1.0], "float32")), pred)
    assert m.get()[1] == pytest.approx(0.5)
    m.reset()
    import math

    assert math.isnan(m.get()[1]) or m.get()[1] == 0.0


def test_topk_accuracy():
    m = gmetric.TopKAccuracy(top_k=2)
    pred = np.array(onp.array([[0.1, 0.2, 0.7],
                               [0.5, 0.4, 0.1]], "float32"))
    lab = np.array(onp.array([1, 2], "float32"))
    m.update(lab, pred)
    assert m.get()[1] == pytest.approx(0.5)


def test_mae_mse_rmse():
    p = onp.array([[1.0], [3.0]], "float32")
    y = onp.array([[2.0], [1.0]], "float32")
    mae = gmetric.MAE()
    mae.update(np.array(y), np.array(p))
    assert mae.get()[1] == pytest.approx(1.5)
    mse = gmetric.MSE()
    mse.update(np.array(y), np.array(p))
    assert mse.get()[1] == pytest.approx(2.5)
    rmse = gmetric.RMSE()
    rmse.update(np.array(y), np.array(p))
    assert rmse.get()[1] == pytest.approx(onp.sqrt(2.5), rel=1e-5)


def test_f1_binary():
    m = gmetric.F1()
    pred = np.array(onp.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]],
                              "float32"))
    lab = np.array(onp.array([1.0, 0.0, 0.0], "float32"))
    m.update(lab, pred)
    # tp=1 fp=1 fn=0 → precision 0.5, recall 1 → f1 = 2/3
    assert m.get()[1] == pytest.approx(2 / 3, rel=1e-5)


def test_mcc_perfect_and_inverse():
    m = gmetric.MCC()
    pred = np.array(onp.array([[0.1, 0.9], [0.9, 0.1]], "float32"))
    lab = np.array(onp.array([1.0, 0.0], "float32"))
    m.update(lab, pred)
    assert m.get()[1] == pytest.approx(1.0)


def test_pearson_correlation():
    m = gmetric.PearsonCorrelation()
    y = onp.array([1.0, 2.0, 3.0, 4.0], "float32")
    p = onp.array([1.1, 1.9, 3.2, 3.8], "float32")
    m.update(np.array(y), np.array(p))
    ref = onp.corrcoef(y, p)[0, 1]
    assert m.get()[1] == pytest.approx(float(ref), rel=1e-4)


def test_perplexity_metric():
    m = gmetric.Perplexity()
    prob = onp.array([[0.5, 0.5], [0.25, 0.75]], "float32")
    lab = onp.array([0.0, 1.0], "float32")
    m.update(np.array(lab), np.array(prob))
    ref = onp.exp(-(onp.log(0.5) + onp.log(0.75)) / 2)
    assert m.get()[1] == pytest.approx(float(ref), rel=1e-4)


def test_cross_entropy_metric():
    m = gmetric.CrossEntropy()
    prob = onp.array([[0.5, 0.5]], "float32")
    m.update(np.array(onp.array([0.0], "float32")), np.array(prob))
    assert m.get()[1] == pytest.approx(-onp.log(0.5), rel=1e-5)


def test_composite_metric():
    c = gmetric.CompositeEvalMetric()
    c.add(gmetric.Accuracy())
    c.add(gmetric.CrossEntropy())      # both take (class-idx, prob) pairs
    pred = np.array(onp.array([[0.9, 0.1]], "float32"))
    lab = np.array(onp.array([0.0], "float32"))
    c.update(lab, pred)
    names, vals = c.get()
    assert len(names) == 2 and len(vals) == 2


def test_metric_create_by_name():
    m = gmetric.create("acc")
    assert isinstance(m, gmetric.Accuracy)

def test_softmax_ce_oob_label_grad_consistent():
    """Out-of-range sparse labels (stray -1 padding): the custom-vjp CE
    must keep forward and backward on the SAME clamped class."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from incubator_mxnet_tpu.gluon.loss import _sparse_softmax_ce

    ce = _sparse_softmax_ce(-1)
    x = jnp.asarray(onp.random.RandomState(0).randn(4, 6), jnp.float32)
    l = jnp.asarray([-1, 0, 5, 9], jnp.int32)       # -1 and 9 are OOB
    lc = jnp.clip(l, 0, 5)
    loss = ce(x, l)
    ref = (jax.scipy.special.logsumexp(x, -1)
           - jnp.take_along_axis(x, lc[:, None], -1)[:, 0])
    onp.testing.assert_allclose(onp.asarray(loss), onp.asarray(ref),
                                rtol=1e-5)
    g = jax.grad(lambda x: ce(x, l).sum())(x)
    p = jax.nn.softmax(x, -1)
    want = onp.array(p, copy=True)
    for i, li in enumerate(onp.asarray(lc)):
        want[i, li] -= 1.0
    onp.testing.assert_allclose(onp.asarray(g), want, atol=1e-5)
