"""telemetry.anatomy — the serving goodput observatory (ISSUE 20).

Stub-gateway tests (pure host arithmetic over REAL
PageAllocator/PrefixCache — the test_gateway.py recipe) gate the
sum-to-wall invariant at <=2% residual across the four request shapes
(plain, preempted, disagg-migrated, spec-decode), the tail-sampling
truth table (a flagged request is ALWAYS archived, normal traffic is
sampled), the disarmed dead branch (begin() returns None and every
seam no-ops) with the literal off-path probe under 3% of a decode
step, role-aware advisor refinement naming the residency series, and
the elastic consume path pinning the spawned replica's role. The
real-engine test is the acceptance gate: on a disaggregated
prefill/decode pod the migrated request's ``handoff_migration`` state
is nonzero, its states sum to its measured wall within 2%, and the
decode replica's residency is decode-dominated.
"""
import json
import os
import sys
import time

import numpy as onp
import pytest

from incubator_mxnet_tpu import serve
from incubator_mxnet_tpu.fault import injection
from incubator_mxnet_tpu.serve.advisor import (RESIDENCY_SERIES,
                                               AutoscaleAdvisor)
from incubator_mxnet_tpu.serve.engine import PageAllocator, PrefixCache
from incubator_mxnet_tpu.telemetry import (anatomy, burnrate, capacity,
                                           registry, timeseries)

VOCAB = 97
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _armed_anatomy():
    injection.clear_injection()
    registry.reset()
    anatomy.reset()
    anatomy.enable()
    anatomy.set_sample(1.0)          # archive everything by default
    yield
    anatomy.disable()
    anatomy.reset()
    anatomy.set_sample(0.05)
    timeseries.disable()
    timeseries.reset()
    burnrate.clear()
    injection.clear_injection()


class _StubSlots:
    """Paged-interface stand-in (same recipe as test_gateway.py):
    final prefill chunk emits the prompt's length, decode increments."""

    def __init__(self, max_slots=2, max_len=64, page_tokens=16,
                 prefill_chunk=64, n_pages=None):
        self.max_slots = max_slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.prefill_chunk = prefill_chunk
        pages_per_slot = -(-max_len // page_tokens)
        self.allocator = PageAllocator(
            n_pages if n_pages is not None
            else max_slots * pages_per_slot + 1, page_tokens)
        self.prefix_cache = PrefixCache(self.allocator)

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        return int(t_start) + n, n, 0

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        pass


class _SpecStubSlots(_StubSlots):
    """Spec-decode stand-in: drafts the correct next token then a wrong
    one, so every round accepts 1 of k=2 — half the round's decode wall
    is carved to ``spec_overhead`` while the invariant still holds."""

    spec_k = 2
    draft_kind = "ngram"

    def spec_propose(self, seqs):
        drafts = onp.zeros((self.max_slots, self.spec_k), onp.int32)
        for s, seq in enumerate(seqs):
            if seq is not None:
                drafts[s, 0] = int(seq[-1]) + 1        # accepted
                drafts[s, 1] = 0                       # rejected
        return drafts

    def spec_verify_step(self, last, drafts, pos, active, limit):
        k = self.spec_k
        out = onp.zeros((self.max_slots, k + 1), onp.int32)
        for s in range(self.max_slots):
            for i in range(k + 1):
                out[s, i] = int(last[s]) + 1 + i
        return out

    def spec_count(self, k, accepted):
        pass


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def _stub_gateway(max_slots=2, slots_cls=_StubSlots, **gw_kwargs):
    reg = serve.ModelRegistry()
    reg.add("m", slots_cls(max_slots=max_slots))
    return serve.Gateway(reg, **gw_kwargs)


def _disagg_gateway(n_prefill=1, n_decode=1):
    stubs = ([_StubSlots() for _ in range(n_prefill)]
             + [_StubSlots() for _ in range(n_decode)])
    reg = serve.ModelRegistry()
    reg.add("m", stubs, prefill_replicas=n_prefill,
            decode_replicas=n_decode)
    return serve.Gateway(reg), stubs


def _drive(gw, handles, steps=400):
    for _ in range(steps):
        gw.step()
        if all(h.done for h in handles):
            return
    raise AssertionError(
        f"requests not done: {[h.state for h in handles]}")


def _gate(rec, tol=0.02):
    """The sum-to-wall invariant: every second of the request's wall is
    attributed to exactly one anatomy state."""
    assert rec is not None
    assert rec.wall_s > 0
    assert abs(rec.residual_s) <= tol * rec.wall_s, (
        rec.residual_s, rec.wall_s, rec.states)
    assert all(v >= 0.0 for v in rec.states.values()), rec.states


# ---------------------------------------------------------------------------
# sum-to-wall across the four request shapes (stub gateway)
# ---------------------------------------------------------------------------

def test_plain_requests_sum_to_wall():
    gw = _stub_gateway()
    try:
        hs = [gw.submit("m", _prompt(4 + i, seed=i), 4)
              for i in range(3)]
        _drive(gw, hs)
    finally:
        gw.shutdown(drain=False)
    for h in hs:
        rec = h._anatomy
        _gate(rec)
        assert rec.outcome == "ok"
        assert rec.states["decode_compute"] > 0.0
        assert rec.states["preempted"] == 0.0
        assert not rec.flags
    rep = registry.report()
    assert rep['mx_request_anatomy_requests_total{outcome="ok"}'][
        "value"] == 3
    # the per-state counter mirrors the per-request ledgers
    total = sum(rep[f'mx_request_anatomy_seconds_total{{state="{s}"}}'][
        "value"] for s in anatomy.STATES
        if f'mx_request_anatomy_seconds_total{{state="{s}"}}' in rep)
    assert total == pytest.approx(sum(r._anatomy.wall_s for r in hs),
                                  rel=0.02)


def test_preempted_request_charges_requeued_wall():
    """The satellite fix: wall spent re-queued after a preemption lands
    in the ``preempted`` state and the victim still sums to wall."""
    gw = _stub_gateway(max_slots=1)
    try:
        low = gw.submit("m", _prompt(4), 8, tenant="crawl",
                        priority="low")
        gw.step()
        assert low.state == "dispatched"
        high = gw.submit("m", _prompt(6, seed=1), 3, tenant="acme",
                         priority="high")
        gw.step()
        assert low.state == "queued" and low.preemptions == 1
        _drive(gw, [low, high])
    finally:
        gw.shutdown(drain=False)
    rec = low._anatomy
    _gate(rec)
    assert "preempted" in rec.flags
    assert rec.states["preempted"] > 0.0
    assert rec.resumes == 1
    _gate(high._anatomy)
    assert "preempted" not in high._anatomy.flags


def test_disagg_migrated_request_sums_to_wall():
    gw, _stubs = _disagg_gateway()
    try:
        hs = [gw.submit("m", _prompt(5 + i, seed=i), 4)
              for i in range(2)]
        _drive(gw, hs)
    finally:
        gw.shutdown(drain=False)
    for h in hs:
        rec = h._anatomy
        _gate(rec)
        assert "migrated" in rec.flags
        assert rec.states["handoff_migration"] > 0.0
    # both shapes of the archive keep a migrated request
    assert {r["id"] for r in anatomy.archive()} >= {h.id for h in hs}


def test_spec_decode_round_carves_overhead():
    gw = _stub_gateway(slots_cls=_SpecStubSlots)
    try:
        h = gw.submit("m", _prompt(4), 6)
        _drive(gw, [h])
    finally:
        gw.shutdown(drain=False)
    rec = h._anatomy
    _gate(rec)
    # every round rejected one of two drafts: waste was carved out of
    # ambient decode_compute, not double-counted on top of it
    assert rec.states["spec_overhead"] > 0.0
    assert rec.states["decode_compute"] >= 0.0


# ---------------------------------------------------------------------------
# tail-sampling truth table + archive bound
# ---------------------------------------------------------------------------

def _fake_request(i, now, outcome="ok", flag=None):
    rec = anatomy.begin(i, "t", "m", "normal", now)
    rec.dispatched(now + 0.01, "m#0")
    rec.prefill_done(now + 0.02)
    if flag is not None:
        rec.requeued(now + 0.03, flag)
        rec.dispatched(now + 0.04, "m#0")
        rec.prefill_done(now + 0.05)
    anatomy.complete(rec, now + 0.1, outcome)
    return rec


def test_tail_sampling_truth_table():
    anatomy.set_sample(0.0)          # drop ALL normal traffic
    _fake_request(0, 0.0)                                  # normal
    _fake_request(1, 1.0, outcome="expired")               # SLO violator
    _fake_request(2, 2.0, flag="preempted")
    _fake_request(3, 3.0, flag="migration_fallback")
    _fake_request(4, 4.0, flag="crash_resume")
    _fake_request(5, 5.0)                                  # normal
    kept = {r["id"] for r in anatomy.archive()}
    assert kept == {1, 2, 3, 4}      # flagged ALWAYS kept, normal never
    # rate 1.0 keeps every normal request
    anatomy.set_sample(1.0)
    _fake_request(6, 6.0)
    assert 6 in {r["id"] for r in anatomy.archive()}
    # rate 0.5 keeps every second NORMAL request, deterministically
    anatomy.reset()
    anatomy.set_sample(0.5)
    for i in range(6):
        _fake_request(i, float(i))
    kept = sorted(r["id"] for r in anatomy.archive())
    assert len(kept) == 3


def test_archive_ring_is_bounded():
    anatomy.set_ring(4)
    try:
        for i in range(10):
            _fake_request(i, float(i), flag="preempted")
        tail = anatomy.archive()
        assert len(tail) == 4
        assert [r["id"] for r in tail] == [6, 7, 8, 9]
    finally:
        anatomy.set_ring(256)


def test_report_and_waterfall_render():
    _fake_request(0, 0.0, flag="preempted")
    anatomy.charge_replica("m#0", "prefill", "prefill", 0.5, now=1.0)
    rep = anatomy.report(now=2.0)
    assert rep["requests_completed"] == 1
    assert rep["replicas"]["m#0"]["role"] == "prefill"
    art = anatomy.format_waterfall(
        next(iter(anatomy.archive())))
    assert "preempted" in art or "P" in art


# ---------------------------------------------------------------------------
# disarmed dead branch + the off-path probe bound
# ---------------------------------------------------------------------------

def test_disarmed_begin_returns_none_and_seams_noop():
    anatomy.disable()
    assert anatomy.begin(0, "t", "m", "normal", 0.0) is None
    anatomy.charge_replica("m#0", "decode", "decode", 1.0, now=1.0)
    assert anatomy.residency_report(now=2.0) == {}
    anatomy.complete(None, 1.0, "ok")        # None record: no-op
    assert anatomy.archive() == []
    # a full gateway run with anatomy off leaves records unset
    gw = _stub_gateway()
    try:
        h = gw.submit("m", _prompt(4), 3)
        _drive(gw, [h])
    finally:
        gw.shutdown(drain=False)
    assert h._anatomy is None
    assert h.result() == [4, 5, 6]


def test_off_path_probe_under_3pct_of_decode_step():
    """The literal disarmed seam — one module-flag check — must cost
    under 3% of even the stub's decode step (min-of-rounds rejects
    load spikes, the test_capacity_observatory recipe)."""
    anatomy.disable()
    capacity.disable()
    slots = _StubSlots()
    last = onp.zeros(2, onp.int32)
    pos = onp.zeros(2, onp.int32)
    active = onp.ones(2, bool)
    iters = 2000
    best_step = float("inf")
    best_probe = float("inf")
    for _round in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            slots.decode_step(last, pos, active, None, 1.0)
        best_step = min(best_step,
                        (time.perf_counter() - t0) / iters)
        t0 = time.perf_counter()
        for _ in range(iters):
            if capacity._ENABLED or anatomy._ENABLED:  # the off path
                pass
        best_probe = min(best_probe,
                         (time.perf_counter() - t0) / iters)
    assert best_probe < 0.03 * best_step, (best_probe, best_step)


# ---------------------------------------------------------------------------
# replica residency + role-aware advisor + elastic consume
# ---------------------------------------------------------------------------

def test_residency_counters_and_fractions():
    anatomy.charge_replica("m#0", "prefill", "prefill", 8.0, now=9.0)
    anatomy.charge_replica("m#1", "decode", "decode", 2.0, now=4.0)
    anatomy.charge_replica("m#1", "decode", "migration", 0.5, now=4.5)
    rep = anatomy.residency_report(now=10.0)
    r0, r1 = rep["m#0"], rep["m#1"]
    assert r0["frac"]["prefill"] == pytest.approx(8.0 / 9.0)
    assert r0["frac"]["idle"] == pytest.approx(1.0 / 9.0)
    # idle is the unexplained remainder of the replica's wall
    assert r1["frac"]["idle"] == pytest.approx(
        1.0 - r1["frac"]["decode"] - r1["frac"]["migration"])
    c = registry.report()[
        'mx_replica_residency_seconds_total'
        '{replica="m#0",role="prefill",state="prefill"}']
    assert c["value"] == pytest.approx(8.0)


def test_advisor_scale_up_refined_by_role_residency():
    """A plain scale_up on a disaggregated pod becomes
    ``scale_up_prefill`` when the prefill-role replicas are markedly
    busier — and the reason names the residency series."""
    timeseries.enable(interval_s=1.0, samples=64, thread=False)
    adv = AutoscaleAdvisor("m", fast_window_s=8.0)
    registry.gauge("mx_serve_slot_occupancy", "occ").set(0.95)
    registry.gauge("mx_gateway_queue_depth", "depth",
                   labels={"priority": "normal"}).set(4)
    for t in range(1, 9):
        timeseries.sample_now(now=float(t))
    # prefill side pinned busy for its whole wall, decode side 25% busy
    anatomy.charge_replica("m#0", "prefill", "prefill", 7.0, now=8.0)
    anatomy.charge_replica("m#1", "decode", "decode", 2.0, now=3.0)
    rec = adv.evaluate(now=8.0)
    assert rec["action"] == "scale_up_prefill"
    assert RESIDENCY_SERIES in rec["reason"]
    assert rec["evidence"][f"{RESIDENCY_SERIES} busy[prefill]"] \
        == pytest.approx(1.0)
    # homogeneous pod (no decode-role rows): the plain action survives
    anatomy.reset()
    anatomy.charge_replica("m#0", "both", "decode", 1.0, now=8.0)
    rec = adv.evaluate(now=8.5)
    assert rec["action"] == "scale_up"
    assert RESIDENCY_SERIES not in rec["reason"]


def test_elastic_consumes_role_action_and_pins_role():
    gw, stubs = _disagg_gateway()
    try:
        ctl = gw.enable_elastic(
            factories={"m": lambda n_pages: _StubSlots(n_pages=n_pages)},
            min_replicas=2, max_replicas=4)
        adv = gw._advisors.get("m")
        if adv is None:
            adv = gw._advisors["m"] = AutoscaleAdvisor("m")
        adv._log.append({"t": 10.0, "action": "scale_up_decode",
                         "model": "m", "n": 1, "reason": "test",
                         "evidence": {}})
        assert ctl.tick(now=11.0) == 1
        reps = gw._models["m"].replicas
        assert reps[-1].role == "decode"
        # acted on exactly once
        assert ctl.tick(now=12.0) == 0
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# real engines: the acceptance gate
# ---------------------------------------------------------------------------

def test_real_engine_disagg_anatomy_gate():
    """On a real disaggregated pod the migrated request's anatomy has a
    nonzero ``handoff_migration`` state, its states sum to its measured
    wall within 2%, and the decode replica's residency is
    decode-dominated among active states."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import np
    from incubator_mxnet_tpu.models.gpt import gpt_tiny

    mx.random.seed(11)
    net = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    net.initialize()
    reg = serve.ModelRegistry(total_pages=40)
    reg.add("gpt", net, prefill_replicas=1, decode_replicas=1,
            max_slots=2, max_len=64)
    gw = serve.Gateway(reg)
    try:
        hs = []
        for i, (n, new) in enumerate([(21, 6), (7, 8)]):
            h = gw.submit("gpt", _prompt(n, seed=1 + i), new)
            gw._drive_until([h], timeout=120.0)
            hs.append(h)
        for h in hs:
            assert h.replica == "gpt#1"       # finished on decode side
            rec = h._anatomy
            _gate(rec)
            assert "migrated" in rec.flags
            assert rec.states["handoff_migration"] > 0.0
            assert rec.states["prefill_compute"] > 0.0
            assert rec.states["decode_compute"] > 0.0
        res = anatomy.residency_report()
        dec = res["gpt#1"]
        assert dec["role"] == "decode"
        active = {s: dec["states"].get(s, 0.0)
                  for s in ("prefill", "decode", "migration", "warmup")}
        assert active["decode"] == max(active.values())
        assert active["prefill"] == 0.0
        # the prefill replica never decoded
        assert res["gpt#0"]["states"].get("decode", 0.0) == 0.0
    finally:
        gw.shutdown(drain=False)


# ---------------------------------------------------------------------------
# reqscope --demo reproducibility (satellite: committed fixture)
# ---------------------------------------------------------------------------

def test_reqscope_demo_is_reproducible_and_committed():
    # The demo drives a virtual clock, so the report is exactly
    # deterministic — the committed fixture must match byte-for-byte
    # (modulo JSON round-tripping of floats, which is itself exact).
    from incubator_mxnet_tpu.telemetry import capacity
    capacity.disable()
    capacity.reset()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import reqscope
    finally:
        sys.path.pop(0)

    rep = reqscope.run_demo()
    assert rep["mode"] == "reqscope-demo"
    assert rep["virtual_clock"] is True
    assert rep["requests_completed"] == 12
    assert rep["archive_depth"] == {"tail": 5, "sampled": 3}

    archive = rep["archive"]
    by_id = {r["id"]: r for r in archive}
    # every flagged request survives tail sampling
    assert "preempted" in by_id[7]["flags"]
    assert "migrated" in by_id[8]["flags"]
    assert "migration_fallback" in by_id[9]["flags"]
    assert "slo_violation" in by_id[10]["flags"]
    assert "crash_resume" in by_id[11]["flags"]
    # 3 of 7 normals kept at sample=0.5 (deterministic stride)
    normal = [r["id"] for r in archive if not r["flags"]]
    assert sorted(normal) == [1, 3, 5]

    with open(os.path.join(REPO, "benchmark", "reqscope_demo.json")) as f:
        committed = json.load(f)
    fresh = json.loads(json.dumps(rep, sort_keys=True))
    assert fresh == committed

    # the rendered report is byte-stable too
    text_fresh = reqscope.format_report(rep)
    text_committed = reqscope.format_report(committed)
    assert text_fresh == text_committed
    assert "replica residency" in text_fresh
    assert "gpt-demo#0" in text_fresh
