"""PixelShuffle/SyncBN/deformable layers + new RNN cells + GroupAdaGrad
tests (reference model: tests/python/unittest/test_gluon.py +
test_contrib_operator.py)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import autograd, gluon, optimizer
from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

nn = gluon.nn
rnn = gluon.rnn


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def rand(*s, seed=0):
    return onp.random.RandomState(seed).randn(*s).astype(onp.float32)


def test_pixel_shuffle_2d_golden():
    # factor 2: channel c*4+2*dy+dx lands at spatial (2y+dy, 2x+dx)
    x = onp.zeros((1, 4, 1, 1), onp.float32)
    x[0, :, 0, 0] = [1, 2, 3, 4]
    out = A(nn.PixelShuffle2D(2)(NDArray(x)))
    onp.testing.assert_array_equal(out[0, 0], [[1, 2], [3, 4]])


def test_pixel_shuffle_roundtrip_shapes():
    assert nn.PixelShuffle1D(3)(NDArray(rand(2, 6, 5))).shape == (2, 2, 15)
    assert nn.PixelShuffle2D((2, 3))(
        NDArray(rand(2, 12, 4, 4))).shape == (2, 2, 8, 12)
    assert nn.PixelShuffle3D(2)(
        NDArray(rand(1, 8, 2, 2, 2))).shape == (1, 1, 4, 4, 4)


def test_batchnorm_relu():
    bn = nn.BatchNormReLU()
    bn.initialize()
    with autograd.record():
        y = bn(NDArray(rand(8, 4, 3, 3)))
    assert float(A(y).min()) >= 0.0


def test_sync_batchnorm_matches_batchnorm():
    x = rand(16, 3, 4, 4, seed=3)
    bn, sbn = nn.BatchNorm(), nn.SyncBatchNorm(num_devices=8)
    bn.initialize()
    sbn.initialize()
    with autograd.record():
        a = bn(NDArray(x))
    with autograd.record():
        b = sbn(NDArray(x))
    onp.testing.assert_allclose(A(a), A(b), rtol=1e-5, atol=1e-5)


def test_deformable_layer_zero_offsets_equals_conv():
    dc = nn.DeformableConvolution(5, (3, 3), padding=(1, 1), use_bias=False)
    dc.initialize()
    x = NDArray(rand(2, 3, 8, 8))
    out = A(dc(x))
    conv = nn.Conv2D(5, (3, 3), padding=(1, 1), use_bias=False)
    conv.initialize()
    conv(x)
    conv.weight.set_data(dc.weight.data())
    onp.testing.assert_allclose(out, A(conv(x)), rtol=2e-2, atol=2e-2)


def test_modulated_deformable_layer_grad_flows():
    mdc = nn.ModulatedDeformableConvolution(4, (3, 3), padding=(1, 1))
    mdc.initialize()
    x = NDArray(rand(1, 2, 6, 6))
    with autograd.record():
        loss = mdc(x).sum()
    loss.backward()
    g = mdc.weight.grad()
    assert float(onp.abs(A(g)).sum()) > 0


def test_conv_dim_cells():
    # every rank × every cell type (1D/3D GRU hit the non-_gates path)
    for cell_cls, cshape, xshape in [
            (rnn.Conv1DRNNCell, (2, 8), (3, 2, 8)),
            (rnn.Conv1DLSTMCell, (2, 8), (3, 2, 8)),
            (rnn.Conv1DGRUCell, (2, 8), (3, 2, 8)),
            (rnn.Conv2DGRUCell, (2, 4, 4), (3, 2, 4, 4)),
            (rnn.Conv2DLSTMCell, (2, 4, 4), (3, 2, 4, 4)),
            (rnn.Conv3DRNNCell, (1, 3, 3, 3), (2, 1, 3, 3, 3)),
            (rnn.Conv3DLSTMCell, (1, 3, 3, 3), (2, 1, 3, 3, 3)),
            (rnn.Conv3DGRUCell, (1, 3, 3, 3), (2, 1, 3, 3, 3))]:
        cell = cell_cls(4, input_shape=cshape)
        cell.initialize()
        x = NDArray(rand(*xshape))
        out, states = cell(x, cell.begin_state(xshape[0]))
        assert out.shape[0] == xshape[0] and out.shape[1] == 4
        # state_info rank matches the actual state rank pre-forward
        fresh = cell_cls(4, input_shape=cshape)
        info = fresh.state_info(2)
        assert len(info[0]["shape"]) == len(cshape) + 1


def test_variational_dropout_resamples_per_unroll():
    import incubator_mxnet_tpu.autograd as ag

    cell = rnn.VariationalDropoutCell(rnn.RNNCell(6, input_size=6),
                                      drop_inputs=0.5)
    cell.initialize()
    x = NDArray(onp.ones((2, 4, 6), onp.float32))
    with ag.record(train_mode=True):
        cell.unroll(4, x)
        m1 = A(cell._mask_i)
        cell.unroll(4, x)
        m2 = A(cell._mask_i)
    assert not onp.array_equal(m1, m2)  # new mask per sequence


def test_lstmp_cell_projection():
    cell = rnn.LSTMPCell(16, 5, input_size=7)
    cell.initialize()
    x = NDArray(rand(4, 7))
    out, states = cell(x, cell.begin_state(4))
    assert out.shape == (4, 5)
    assert states[0].shape == (4, 5) and states[1].shape == (4, 16)
    out2, _ = cell.unroll(3, NDArray(rand(4, 3, 7)))
    assert out2.shape == (4, 3, 5)


def test_variational_dropout_same_mask_across_steps():
    import incubator_mxnet_tpu.autograd as ag

    base = rnn.RNNCell(6, input_size=6)
    cell = rnn.VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize()
    x = NDArray(onp.ones((2, 6), onp.float32))
    with ag.record(train_mode=True):
        cell(x, cell.begin_state(2))
        m1 = cell._mask_i
        cell(x, cell.begin_state(2))
        m2 = cell._mask_i
    assert m1 is not None
    onp.testing.assert_array_equal(A(m1), A(m2))  # mask reused
    cell.reset()
    assert cell._mask_i is None


def test_modifier_cell_state_info():
    base = rnn.LSTMCell(8, input_size=4)
    mod = rnn.VariationalDropoutCell(base)
    assert mod.state_info(2) == base.state_info(2)
    assert isinstance(mod, rnn.ModifierCell)


def test_group_adagrad():
    opt = optimizer.create("groupadagrad", learning_rate=0.1)
    w = NDArray(rand(6, 4, seed=1))
    g = NDArray(rand(6, 4, seed=2))
    state = opt.create_state(0, w)
    assert state[0].shape == (6, 1)  # one history scalar per row
    w2, state2 = opt.step(w._data, g._data, state, 0.1, 0.0, 1)
    assert w2.shape == (6, 4)
    assert float(onp.abs(onp.asarray(state2[0])).sum()) > 0


def test_ftrl_alias():
    assert optimizer.Ftrl is optimizer.FTRL
    assert isinstance(optimizer.create("ftrl"), optimizer.FTRL)


def test_nested_modifier_reset_recurses():
    """reset() must reach wrapped cells (reference reset walks children):
    a VariationalDropoutCell inside a SequentialRNNCell resamples its
    mask every unroll."""
    import incubator_mxnet_tpu.autograd as ag

    seq = rnn.SequentialRNNCell()
    inner = rnn.VariationalDropoutCell(rnn.RNNCell(6, input_size=6),
                                       drop_inputs=0.5)
    seq.add(inner)
    seq.initialize()
    x = NDArray(onp.ones((2, 4, 6), onp.float32))
    with ag.record(train_mode=True):
        seq.unroll(4, x)
        m1 = A(inner._mask_i)
        seq.unroll(4, x)
        m2 = A(inner._mask_i)
    assert not onp.array_equal(m1, m2)


def test_zoneout_reset_clears_prev_output():
    import incubator_mxnet_tpu.autograd as ag

    cell = rnn.ZoneoutCell(rnn.RNNCell(4, input_size=4),
                           zoneout_outputs=0.5)
    cell.initialize()
    x = NDArray(onp.ones((2, 3, 4), onp.float32))
    with ag.record(train_mode=True):
        cell.unroll(3, x)
        assert cell._prev_output is not None
    cell.reset()
    assert cell._prev_output is None
