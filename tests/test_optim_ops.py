"""Functional optimizer-update ops (reference `src/operator/
optimizer_op.cc` + contrib multi/preloaded/adamw/lamb/lans families).

Each rule is pinned against a plain-numpy oracle of the reference
kernel math; in-place state mutation and `out=` semantics are checked
explicitly.
"""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np

nd = mx.nd


def _w(shape=(4, 3), seed=0):
    return np.array(onp.random.RandomState(seed)
                    .uniform(-1, 1, shape).astype("float32"))


def test_sgd_update_out_semantics():
    w, g = _w(), _w(seed=1)
    wn, gn = w.asnumpy(), g.asnumpy()
    out = nd.sgd_update(w, g, lr=0.1, wd=0.01, out=w)
    assert out is w
    onp.testing.assert_allclose(
        w.asnumpy(), wn - 0.1 * (gn + 0.01 * wn), rtol=1e-5)


def test_sgd_update_clip_and_rescale():
    w, g = _w(), _w(seed=1)
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.sgd_update(w, g, lr=1.0, rescale_grad=4.0, clip_gradient=0.5,
                  out=w)
    expect = wn - onp.clip(4.0 * gn, -0.5, 0.5)
    onp.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-5)


def test_sgd_mom_update_state_mutation():
    w, g, m = _w(), _w(seed=1), np.zeros((4, 3))
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9, out=w)
    m1 = -0.1 * gn
    onp.testing.assert_allclose(m.asnumpy(), m1, rtol=1e-5)
    onp.testing.assert_allclose(w.asnumpy(), wn + m1, rtol=1e-5)
    # second step uses the mutated momentum
    nd.sgd_mom_update(w, g, m, lr=0.1, momentum=0.9, out=w)
    m2 = 0.9 * m1 - 0.1 * gn
    onp.testing.assert_allclose(m.asnumpy(), m2, rtol=1e-5)


def test_mp_sgd_update_master_weights():
    w32 = _w()
    w16 = np.array(w32.asnumpy().astype("float16"))
    g = _w(seed=1)
    nd.mp_sgd_update(w16, g, w32, lr=0.1, out=w16)
    onp.testing.assert_allclose(
        w32.asnumpy(),
        _w().asnumpy() - 0.1 * g.asnumpy(), rtol=1e-5)
    onp.testing.assert_allclose(w16.asnumpy(),
                                w32.asnumpy().astype("float16"),
                                rtol=1e-3)
    assert str(w16.dtype).endswith("float16")


def test_adam_update_oracle():
    w, g = _w(), _w(seed=1)
    m, v = np.zeros((4, 3)), np.zeros((4, 3))
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.adam_update(w, g, m, v, lr=0.01, beta1=0.9, beta2=0.999,
                   epsilon=1e-8, out=w)
    m1 = 0.1 * gn
    v1 = 0.001 * gn * gn
    onp.testing.assert_allclose(m.asnumpy(), m1, rtol=1e-5)
    onp.testing.assert_allclose(v.asnumpy(), v1, rtol=1e-4)
    onp.testing.assert_allclose(
        w.asnumpy(), wn - 0.01 * m1 / (onp.sqrt(v1) + 1e-8), rtol=1e-5)


def test_adamw_nan_scale_skips_update():
    w, g = _w(), _w(seed=1)
    m, v = np.zeros((4, 3)), np.zeros((4, 3))
    wn = w.asnumpy()
    scale = np.array(onp.array(onp.nan, "float32"))
    nd.adamw_update(w, g, m, v, scale, lr=0.01, eta=1.0, out=w)
    onp.testing.assert_allclose(w.asnumpy(), wn)   # untouched
    onp.testing.assert_allclose(m.asnumpy(), 0 * wn)


def test_adamw_decoupled_decay():
    w, g = _w(), _w(seed=1)
    m, v = np.zeros((4, 3)), np.zeros((4, 3))
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.adamw_update(w, g, m, v, 1.0, lr=0.01, eta=1.0, wd=0.1, out=w)
    m1, v1 = 0.1 * gn, 0.001 * gn * gn
    expect = wn - (0.01 * m1 / (onp.sqrt(v1) + 1e-8) + 0.1 * wn)
    onp.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-5)


def test_signsgd_signum():
    w, g = _w(), _w(seed=1)
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.signsgd_update(w, g, lr=0.1, out=w)
    onp.testing.assert_allclose(w.asnumpy(), wn - 0.1 * onp.sign(gn),
                                rtol=1e-5)
    w2, m = _w(seed=2), np.zeros((4, 3))
    w2n = w2.asnumpy()
    nd.signum_update(w2, g, m, lr=0.1, momentum=0.9, out=w2)
    m1 = -0.1 * gn
    onp.testing.assert_allclose(m.asnumpy(), m1, rtol=1e-5)
    onp.testing.assert_allclose(w2.asnumpy(),
                                w2n + 0.1 * onp.sign(m1), rtol=1e-5)


def test_ftrl_sparsifies():
    w = np.array(onp.full((4, 3), 0.5, "float32"))
    g = np.array(onp.full((4, 3), 1e-4, "float32"))
    z, n = np.zeros((4, 3)), np.zeros((4, 3))
    nd.ftrl_update(w, g, z, n, lr=0.1, lamda1=1.0, out=w)
    # tiny gradient + strong l1 → weights snap to exactly 0
    assert onp.abs(w.asnumpy()).max() == 0.0


def test_rmsprop_update():
    w, g = _w(), _w(seed=1)
    n = np.zeros((4, 3))
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.rmsprop_update(w, g, n, lr=0.01, gamma1=0.9, epsilon=1e-8,
                      out=w)
    n1 = 0.1 * gn * gn
    onp.testing.assert_allclose(n.asnumpy(), n1, rtol=1e-4)
    onp.testing.assert_allclose(
        w.asnumpy(), wn - 0.01 * gn / onp.sqrt(n1 + 1e-8), rtol=1e-4)


def test_rmspropalex_update_runs():
    w, g = _w(), _w(seed=1)
    n, gb, d = np.zeros((4, 3)), np.zeros((4, 3)), np.zeros((4, 3))
    before = w.asnumpy().copy()
    nd.rmspropalex_update(w, g, n, gb, d, lr=0.01, out=w)
    assert not onp.allclose(w.asnumpy(), before)
    assert onp.isfinite(w.asnumpy()).all()


def test_ftml_update_runs():
    w, g = _w(), _w(seed=1)
    d, v, z = np.zeros((4, 3)), np.zeros((4, 3)), np.zeros((4, 3))
    before = w.asnumpy().copy()
    nd.ftml_update(w, g, d, v, z, lr=0.01, t=1, out=w)
    assert not onp.allclose(w.asnumpy(), before)
    assert onp.isfinite(w.asnumpy()).all()


def test_lamb_phases():
    w, g = _w(), _w(seed=1)
    m, v = np.zeros((4, 3)), np.zeros((4, 3))
    gdir = nd.lamb_update_phase1(w, g, m, v, t=1, wd=0.01)
    assert onp.isfinite(gdir.asnumpy()).all()
    r1 = np.array(onp.array(
        onp.linalg.norm(w.asnumpy()), "float32"))
    r2 = np.array(onp.array(
        onp.linalg.norm(gdir.asnumpy()), "float32"))
    wn = w.asnumpy().copy()
    nd.lamb_update_phase2(w, gdir, r1, r2, lr=0.01, out=w)
    ratio = float(r1.asnumpy()) / float(r2.asnumpy())
    onp.testing.assert_allclose(
        w.asnumpy(), wn - 0.01 * ratio * gdir.asnumpy(), rtol=1e-4)


def test_multi_sgd_update():
    ws = [_w(seed=i) for i in range(2)]
    gs = [_w(seed=10 + i) for i in range(2)]
    before = [w.asnumpy().copy() for w in ws]
    nd.multi_sgd_update(ws[0], gs[0], ws[1], gs[1],
                        lrs=(0.1, 0.2), wds=(0.0, 0.0),
                        num_weights=2, out=ws)
    for i, (w, g) in enumerate(zip(ws, gs)):
        onp.testing.assert_allclose(
            w.asnumpy(), before[i] - (0.1, 0.2)[i] * g.asnumpy(),
            rtol=1e-5)


def test_preloaded_multi_sgd():
    ws = [_w(seed=i) for i in range(2)]
    gs = [_w(seed=10 + i) for i in range(2)]
    before = [w.asnumpy().copy() for w in ws]
    lrs = np.array(onp.array([0.1, 0.2], "float32"))
    wds = np.array(onp.array([0.0, 0.0], "float32"))
    nd.preloaded_multi_sgd_update(ws[0], gs[0], ws[1], gs[1], lrs, wds,
                                  num_weights=2, out=ws)
    for i, (w, g) in enumerate(zip(ws, gs)):
        onp.testing.assert_allclose(
            w.asnumpy(), before[i] - (0.1, 0.2)[i] * g.asnumpy(),
            rtol=1e-5)


def test_multi_sum_sq_and_lars():
    a, b = _w(), _w(seed=1)
    ss = nd.multi_sum_sq(a, b, num_arrays=2)
    onp.testing.assert_allclose(
        ss.asnumpy(),
        [(a.asnumpy() ** 2).sum(), (b.asnumpy() ** 2).sum()], rtol=1e-4)
    lrs = np.array(onp.array([0.1, 0.1], "float32"))
    wds = np.array(onp.array([0.0, 0.0], "float32"))
    g2 = nd.multi_sum_sq(b, a, num_arrays=2)
    new = nd.multi_lars(lrs, ss, g2, wds, eta=0.01)
    assert new.shape == (2,)
    assert (new.asnumpy() > 0).all()


def test_reset_arrays():
    a, b = _w(), _w(seed=1)
    nd.reset_arrays(a, b, num_arrays=2)
    assert onp.abs(a.asnumpy()).max() == 0.0
    assert onp.abs(b.asnumpy()).max() == 0.0


def test_sparse_adagrad_update_rowsparse():
    from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray

    w = _w((5, 3))
    h = np.zeros((5, 3))
    wn = w.asnumpy().copy()
    vals = onp.ones((2, 3), "float32")
    idx = onp.array([1, 3], "int32")
    g = RowSparseNDArray(vals, idx, (5, 3))
    nd.sparse_adagrad_update(w, g, h, lr=0.1, epsilon=1e-7, out=w)
    touched = w.asnumpy()[[1, 3]]
    onp.testing.assert_allclose(
        touched, wn[[1, 3]] - 0.1 * 1.0 / (onp.sqrt(1.0) + 1e-7),
        rtol=1e-5)
    onp.testing.assert_allclose(w.asnumpy()[[0, 2, 4]],
                                wn[[0, 2, 4]])  # untouched rows
    onp.testing.assert_allclose(h.asnumpy()[[1, 3]],
                                onp.ones((2, 3)), rtol=1e-6)


def test_group_adagrad_update():
    w, g = _w(), _w(seed=1)
    h = np.zeros((4,))
    wn, gn = w.asnumpy(), g.asnumpy()
    nd.group_adagrad_update(w, g, h, lr=0.1, out=w)
    h1 = (gn * gn).mean(axis=1)
    onp.testing.assert_allclose(h.asnumpy(), h1, rtol=1e-4)
    onp.testing.assert_allclose(
        w.asnumpy(), wn - 0.1 * gn / onp.sqrt(h1 + 1e-5)[:, None],
        rtol=1e-4)


def test_square_sum():
    x = _w()
    out = nd.square_sum(x, axis=1)
    onp.testing.assert_allclose(out.asnumpy(),
                                (x.asnumpy() ** 2).sum(axis=1),
                                rtol=1e-5)


def test_multi_lamb_and_lans_run():
    ws = [_w(seed=i) for i in range(2)]
    gs = [_w(seed=10 + i) for i in range(2)]
    ms = [np.zeros((4, 3)) for _ in range(2)]
    vs = [np.zeros((4, 3)) for _ in range(2)]
    before = [w.asnumpy().copy() for w in ws]
    nd.multi_lamb_update(
        ws[0], gs[0], ms[0], vs[0], ws[1], gs[1], ms[1], vs[1],
        learning_rates=(0.01, 0.01), wds=(0.0, 0.0),
        step_count=(1, 1), num_tensors=2, out=ws)
    for i, w in enumerate(ws):
        assert not onp.allclose(w.asnumpy(), before[i])
        assert onp.isfinite(w.asnumpy()).all()
    ws2 = [_w(seed=i) for i in range(2)]
    nd.multi_lans_update(
        ws2[0], gs[0], ms[0], vs[0], ws2[1], gs[1], ms[1], vs[1],
        learning_rates=(0.01, 0.01), wds=(0.0, 0.0),
        step_count=(1, 1), num_tensors=2, out=ws2)
    for w in ws2:
        assert onp.isfinite(w.asnumpy()).all()
