"""Parallelism tests on the virtual 8-device CPU mesh (the reference tests
distributed code with multi-process-on-localhost, SURVEY.md §4; here the
equivalent is an 8-device virtual platform)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.parallel import (
    all_gather, all_reduce, make_mesh, mesh_scope, ring_permute,
    shard_train_step,
)
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def _need_8():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")


def test_make_mesh():
    _need_8()
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.shape["dp"] == 2
    assert mesh.shape["tp"] == 4
    mesh2 = make_mesh({"dp": -1})
    assert mesh2.shape["dp"] == 8
    # (axis, size) pairs are accepted too
    mesh3 = make_mesh([("a", 2), ("b", -1)])
    assert mesh3.shape["a"] == 2 and mesh3.shape["b"] == 4


def test_make_mesh_wildcard_divisibility_error():
    """8 devices with a known axis of 3: the wildcard cannot divide
    evenly — the error must name the wildcard axis and the divisor, not
    the misleading truncated 'needs N devices' message (ISSUE 8)."""
    _need_8()
    with pytest.raises(ValueError, match="wildcard axis 'dp'.*divisible"):
        make_mesh({"dp": -1, "tp": 3})
    with pytest.raises(ValueError, match="at most one -1"):
        make_mesh({"dp": -1, "tp": -1})


def test_make_mesh_duplicate_axis_error():
    _need_8()
    with pytest.raises(ValueError, match="unique.*dp"):
        make_mesh([("dp", 2), ("dp", 4)])


def test_data_parallel_picks_up_ambient_mesh_scope():
    """DataParallel(mesh=None) under `with mesh_scope(m)` must train on
    m, not silently single-chip (ISSUE 8 satellite)."""
    _need_8()
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    mesh = make_mesh({"dp": 8})
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    o = mx.optimizer.SGD(learning_rate=0.5)
    with mesh_scope(mesh):
        dp = DataParallel(net, gluon.loss.L2Loss(), o)
    assert dp.mesh is mesh
    # the batch sharding the jit was built with spans the ambient mesh
    assert dp._batch_sharding is not None
    assert dp._batch_sharding.mesh is mesh
    X = onp.zeros((8, 4), "float32")
    loss = dp.step(np.array(X), np.array(X[:, :1]))
    assert onp.isfinite(float(loss.item()))
    # outside any scope, mesh=None still means single-chip
    net2 = gluon.nn.Dense(1, in_units=4)
    net2.initialize()
    dp2 = DataParallel(net2, gluon.loss.L2Loss(), mx.optimizer.SGD())
    assert dp2.mesh is None


def test_allreduce_shard_map():
    _need_8()
    import jax

    mesh = make_mesh({"dp": 8})

    def step(x):
        return all_reduce(x, "dp")

    f = shard_train_step(step, mesh, in_specs=[("dp",)], out_specs=("dp",))
    x = onp.arange(8, dtype="float32")
    out = onp.asarray(f(x))
    assert_almost_equal(out, onp.full(8, x.sum()))


def test_allgather_and_ring():
    _need_8()
    mesh = make_mesh({"dp": 8})

    def gather_step(x):
        return all_gather(x, "dp", axis=0)

    f = shard_train_step(gather_step, mesh, in_specs=[("dp",)],
                         out_specs=("dp",))
    x = onp.arange(8, dtype="float32")
    out = onp.asarray(f(x))
    # every device holds the full gathered vector; concatenated: tiled 8×
    assert out.shape == (64,)
    assert_almost_equal(out[:8], x)
    assert_almost_equal(out[8:16], x)

    def ring_step(x):
        return ring_permute(x, "dp", shift=1)

    g = shard_train_step(ring_step, mesh, in_specs=[("dp",)],
                         out_specs=("dp",))
    out = onp.asarray(g(x))
    # shard i moves to device (i+1) % 8
    assert_almost_equal(out, onp.roll(x, 1))


def test_data_parallel_trainer():
    _need_8()
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    mesh = make_mesh({"dp": 8})
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    o = mx.optimizer.SGD(learning_rate=0.5)
    dp = DataParallel(net, gluon.loss.L2Loss(), o, mesh=mesh)
    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 4)).astype("float32")
    true_w = onp.array([[1.0, 2.0, -1.0, 0.5]], dtype="float32")
    Y = X @ true_w.T
    first = None
    for i in range(150):
        loss = dp.step(np.array(X), np.array(Y))
        if first is None:
            first = float(loss.item())
    last = float(loss.item())
    assert last < first * 0.01, (first, last)
    assert_almost_equal(net.weight.data().asnumpy(), true_w, rtol=5e-2,
                        atol=5e-2)


def test_sharded_bert_multichip():
    _need_8()
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_graft_entry_compiles():
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[0].shape[0]


def test_kvstore_api():
    kv = mx.kv.create("device")
    a = np.ones((3,))
    kv.init("w", a)
    out = np.zeros((3,))
    kv.pull("w", out=out)
    assert_almost_equal(out.asnumpy(), onp.ones(3))
    kv.pushpull("g", np.full((3,), 2.0), out=out)
    assert_almost_equal(out.asnumpy(), onp.full(3, 2.0))
    assert kv.rank == 0
    assert kv.num_workers == 1
    # optimizer on kvstore
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.init("p", np.ones((2,)))
    kv.push("p", np.full((2,), 1.0))
    pulled = np.zeros((2,))
    kv.pull("p", out=pulled)
    assert_almost_equal(pulled.asnumpy(), onp.full(2, 0.9), rtol=1e-5)


def test_data_parallel_adam_traced_t():
    """ADVICE r1 (high): Adam bias correction must accept a traced step
    counter — DataParallel passes t through jit."""
    _need_8()
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    mesh = make_mesh({"dp": 8})
    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    o = mx.optimizer.Adam(learning_rate=0.05)
    dp = DataParallel(net, gluon.loss.L2Loss(), o, mesh=mesh)
    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (64, 4)).astype("float32")
    Y = X @ onp.array([[1.0, 2.0, -1.0, 0.5]], dtype="float32").T
    first = None
    for _ in range(100):
        loss = dp.step(np.array(X), np.array(Y))
        if first is None:
            first = float(loss.item())
    assert float(loss.item()) < first * 0.1


def test_data_parallel_batchnorm_aux_updates():
    """ADVICE r1 (medium): BatchNorm running stats must update under
    DataParallel (functionalized aux writeback)."""
    _need_8()
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    mesh = make_mesh({"dp": 8})
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, in_units=4), gluon.nn.BatchNorm(in_channels=8),
            gluon.nn.Dense(1, in_units=8))
    net.initialize()
    bn = list(net._children.values())[1]
    before = bn.running_mean.data().asnumpy().copy()
    o = mx.optimizer.SGD(learning_rate=0.1)
    dp = DataParallel(net, gluon.loss.L2Loss(), o, mesh=mesh)
    rng = onp.random.RandomState(0)
    X = (rng.uniform(-1, 1, (64, 4)) + 3.0).astype("float32")
    Y = rng.uniform(-1, 1, (64, 1)).astype("float32")
    for _ in range(3):
        dp.step(np.array(X), np.array(Y))
    after = bn.running_mean.data().asnumpy()
    delta = float(onp.abs(after - before).max())
    assert delta > 1e-6, "running stats did not update"


def test_data_parallel_live_lr():
    """ADVICE r1 (medium): set_learning_rate must take effect between
    steps without retracing, and num_update must advance."""
    _need_8()
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    mesh = make_mesh({"dp": 8})
    net = gluon.nn.Dense(1, in_units=4, use_bias=False)
    net.initialize()
    o = mx.optimizer.SGD(learning_rate=0.1)
    dp = DataParallel(net, gluon.loss.L2Loss(), o, mesh=mesh)
    rng = onp.random.RandomState(0)
    X = rng.uniform(-1, 1, (8, 4)).astype("float32")
    Y = rng.uniform(-1, 1, (8, 1)).astype("float32")
    dp.step(np.array(X), np.array(Y))
    assert o.num_update == 1
    w1 = net.weight.data().asnumpy().copy()
    o.set_learning_rate(0.0)  # freeze: next step must be a no-op update
    dp.step(np.array(X), np.array(Y))
    w2 = net.weight.data().asnumpy()
    assert_almost_equal(w1, w2)
    assert o.num_update == 2


def test_data_parallel_matches_single_device():
    """VERDICT r1: multi-device training must match single-device training
    (÷ batch) — same data, same init, SGD; eager Trainer vs 8-device
    DataParallel."""
    _need_8()
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    rng = onp.random.RandomState(3)
    X = rng.uniform(-1, 1, (32, 6)).astype("float32")
    Y = rng.uniform(-1, 1, (32, 1)).astype("float32")
    W0 = rng.uniform(-0.1, 0.1, (1, 6)).astype("float32")

    def make_net():
        net = gluon.nn.Dense(1, in_units=6, use_bias=False)
        net.initialize()
        net.weight.set_data(np.array(W0))
        return net

    # single device, eager Trainer (loss mean over batch)
    net_a = make_net()
    trainer = gluon.Trainer(net_a.collect_params(),
                            mx.optimizer.SGD(learning_rate=0.2))
    loss_fn = gluon.loss.L2Loss()
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net_a(np.array(X)), np.array(Y)).mean()
        loss.backward()
        trainer.step(1)

    # 8-device data-parallel compiled step
    net_b = make_net()
    dp = DataParallel(net_b, gluon.loss.L2Loss(),
                      mx.optimizer.SGD(learning_rate=0.2),
                      mesh=make_mesh({"dp": 8}))
    for _ in range(5):
        dp.step(np.array(X), np.array(Y))

    assert_almost_equal(net_a.weight.data().asnumpy(),
                        net_b.weight.data().asnumpy(), rtol=1e-5, atol=1e-6)


def test_gluon_bert_tp_dataparallel_matches_replicated():
    """Gluon BERT trained through DataParallel with Megatron TP param
    shardings + SP activation constraints must match the fully-replicated
    DataParallel run (same init/data) — TP/SP is a layout, not math."""
    _need_8()
    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.models.bert import bert_small, tp_param_shardings
    from incubator_mxnet_tpu.parallel import DataParallel

    rng = onp.random.RandomState(0)
    tokens = np.array(rng.randint(0, 64, (8, 16)).astype("int32"))
    labels = np.array(rng.randint(0, 64, (8, 16)).astype("int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(out, y):
        mlm_scores, _ = out
        return ce(mlm_scores.reshape(-1, 64), y.reshape(-1))

    def run(shardings, mesh_axes, seq_axis):
        from incubator_mxnet_tpu import npx

        npx.seed(7)
        net = bert_small(vocab_size=64, max_length=32, dropout=0.0,
                         seq_shard_axis=seq_axis)
        net.initialize()
        mesh = make_mesh(mesh_axes)
        dp = DataParallel(net, mlm_loss, optimizer.SGD(learning_rate=0.1),
                          mesh=mesh,
                          param_shardings=(tp_param_shardings(net)
                                           if shardings else None))
        losses = [float(dp.step(tokens, labels).asnumpy())
                  for _ in range(3)]
        return losses, net

    losses_tp, net_tp = run(True, {"dp": 2, "tp": 4}, "tp")
    losses_rep, net_rep = run(False, {"dp": 8}, None)
    onp.testing.assert_allclose(losses_tp, losses_rep, rtol=2e-4, atol=2e-4)
    for (n1, p1), (n2, p2) in zip(net_tp.collect_params().items(),
                                  net_rep.collect_params().items()):
        onp.testing.assert_allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                                    rtol=3e-3, atol=3e-4, err_msg=n1)
    assert losses_tp[-1] < losses_tp[0]  # it actually learns


def test_fused_small_param_update_matches_unfused():
    """Multi-tensor fused small-param updates (reference aggregate_num
    role) are EXACT: the same net trained with fusion enabled (Adam,
    elementwise) and disabled must land on identical weights."""
    import numpy as onp

    from incubator_mxnet_tpu import gluon, np, optimizer
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    def train(elementwise):
        mx.random.seed(5)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
                gluon.nn.LayerNorm(in_channels=16),
                gluon.nn.Dense(4, in_units=16))
        net.initialize()
        opt = optimizer.Adam(learning_rate=1e-2)
        opt.elementwise = elementwise   # False => per-param path
        dp = DataParallel(net, gluon.loss.L2Loss(), opt)
        rng = onp.random.RandomState(2)
        x = np.array(rng.uniform(-1, 1, (8, 8)).astype("float32"))
        y = np.array(rng.uniform(-1, 1, (8, 4)).astype("float32"))
        for _ in range(5):
            dp.step(x, y)
        return {k: p.data().asnumpy()
                for k, p in net.collect_params().items()}

    fused = train(True)
    plain = train(False)
    assert fused.keys() == plain.keys()
    for k in fused:
        # identical math; XLA reassociation in the fused kernel shifts
        # the last ulp (~4e-9 observed)
        onp.testing.assert_allclose(fused[k], plain[k], rtol=1e-6,
                                    atol=1e-7, err_msg=k)
