"""Pallas flash attention: numerics vs the XLA softmax path, gradients,
masking, and integration with the gluon BERT model.

Runs in pallas interpret mode on the CPU test mesh (conftest forces the
cpu platform); the same kernels compile on TPU (verified on-chip).
Reference test pattern: consistency testing between two implementations
of the same op (`python/mxnet/test_utils.py:1491 check_consistency`)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

from incubator_mxnet_tpu import autograd, np, npx
from incubator_mxnet_tpu.ops import flash_attention


def _naive(q, k, v, lengths=None, causal=False):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(d)
    mask = jnp.ones((b, 1, tq, tk), bool)
    if lengths is not None:
        cols = jnp.arange(tk)[None, None, None, :]
        rows = jnp.arange(tq)[None, None, :, None]
        lens = lengths[:, None, None, None]
        mask = (cols < lens) & (rows < lens)
    if causal:
        mask = mask & (jnp.arange(tk)[None, None, None, :]
                       <= jnp.arange(tq)[None, None, :, None])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture
def qkv():
    rng = onp.random.RandomState(7)
    return tuple(jnp.asarray(rng.randn(2, 3, 96, 32).astype("float32"))
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_naive(qkv, causal):
    q, k, v = qkv
    o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    onp.testing.assert_allclose(o, _naive(q, k, v, causal=causal),
                                atol=2e-5, rtol=2e-5)


def test_forward_with_lengths(qkv):
    q, k, v = qkv
    lens = jnp.asarray([50, 96], jnp.int32)
    o = flash_attention(q, k, v, lengths=lens, block_q=32, block_k=32)
    onp.testing.assert_allclose(o, _naive(q, k, v, lengths=lens),
                                atol=2e-5, rtol=2e-5)
    # rows past the valid length are exactly zero
    assert float(jnp.abs(o[0, :, 50:]).max()) == 0.0


def test_non_divisible_seq_len_padding():
    rng = onp.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(1, 2, 75, 16).astype("float32"))
               for _ in range(3))
    o = flash_attention(q, k, v, block_q=32, block_k=32)
    onp.testing.assert_allclose(o, _naive(q, k, v), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_naive(qkv, causal):
    q, k, v = qkv
    lens = jnp.asarray([50, 96], jnp.int32)

    def lf(q, k, v):
        return (flash_attention(q, k, v, lengths=lens, causal=causal,
                                block_q=32, block_k=32) ** 2).sum()

    def ln(q, k, v):
        return (_naive(q, k, v, lengths=lens, causal=causal) ** 2).sum()

    gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(ln, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        onp.testing.assert_allclose(a, b, atol=5e-4, rtol=1e-3)


def test_under_jit(qkv):
    q, k, v = qkv
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, block_q=32,
                                                block_k=32))
    onp.testing.assert_allclose(f(q, k, v), _naive(q, k, v),
                                atol=2e-5, rtol=2e-5)


def test_npx_flash_attention_autograd():
    rng = onp.random.RandomState(11)
    q = np.array(rng.randn(2, 2, 32, 16).astype("float32"))
    k = np.array(rng.randn(2, 2, 32, 16).astype("float32"))
    v = np.array(rng.randn(2, 2, 32, 16).astype("float32"))
    for t in (q, k, v):
        t.attach_grad()
    with autograd.record():
        out = npx.flash_attention(q, k, v)
        loss = (out * out).sum()
    loss.backward()
    gn = jax.grad(lambda q, k, v: (_naive(q, k, v) ** 2).sum(),
                  argnums=(0, 1, 2))(q._data, k._data, v._data)
    for t, g in zip((q, k, v), gn):
        onp.testing.assert_allclose(t.grad.asnumpy(), g, atol=5e-4,
                                    rtol=1e-3)


def test_bert_flash_vs_dense_mask():
    """Gluon BERT with flash attention == same weights with the dense-mask
    softmax path (dropout=0)."""
    from incubator_mxnet_tpu.models.bert import bert_small

    net_f = bert_small(dropout=0.0, use_flash=True)
    net_d = bert_small(dropout=0.0, use_flash=False)
    net_f.initialize()
    rng = onp.random.RandomState(0)
    tokens = np.array(rng.randint(0, 1000, (2, 48)).astype("int32"))
    vlen = np.array(onp.array([30, 48]).astype("int32"))
    mlm_f, nsp_f = net_f(tokens, None, vlen)
    # copy params across
    src = net_f.collect_params()
    dst = net_d.collect_params()
    net_d.initialize()
    for name, p in dst.items():
        p.set_data(src[name].data())
    mlm_d, nsp_d = net_d(tokens, None, vlen)
    # only compare valid rows: masked-out rows differ by construction
    onp.testing.assert_allclose(mlm_f.asnumpy()[0, :30],
                                mlm_d.asnumpy()[0, :30], atol=2e-4,
                                rtol=2e-3)
    onp.testing.assert_allclose(nsp_f.asnumpy(), nsp_d.asnumpy(),
                                atol=2e-4, rtol=2e-3)


def test_impl_dispatch_xla_matches_pallas():
    """auto → XLA path for small T; both impls agree numerically."""
    import jax.numpy as jnp

    import importlib

    fa = importlib.import_module("incubator_mxnet_tpu.ops.flash_attention")

    rng = onp.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 2, 64, 16).astype(onp.float32))
    k = jnp.asarray(rng.randn(2, 2, 64, 16).astype(onp.float32))
    v = jnp.asarray(rng.randn(2, 2, 64, 16).astype(onp.float32))
    lens = jnp.asarray([40, 64], jnp.int32)
    for kwargs in ({"causal": True}, {"lengths": lens}, {}):
        a = fa.flash_attention(q, k, v, impl="xla", **kwargs)
        b = fa.flash_attention(q, k, v, impl="pallas", **kwargs)
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=2e-3, atol=2e-4)


def test_impl_auto_thresholds():
    import jax.numpy as jnp

    import importlib

    fa = importlib.import_module("incubator_mxnet_tpu.ops.flash_attention")

    # tiny input → auto must resolve to the XLA path (no pallas tracing)
    q = jnp.ones((1, 1, 8, 4), jnp.float32)
    out = fa.flash_attention(q, q, q, impl="auto")
    assert out.shape == (1, 1, 8, 4)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unknown impl"):
        fa.flash_attention(q, q, q, impl="nope")


def test_xla_impl_grad_flows():
    import jax
    import jax.numpy as jnp

    import importlib

    fa = importlib.import_module("incubator_mxnet_tpu.ops.flash_attention")

    rng = onp.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 2, 32, 8).astype(onp.float32))

    def loss(x):
        return fa.flash_attention(x, x, x, causal=True, impl="xla").sum()

    g = jax.grad(loss)(q)
    assert float(jnp.abs(g).sum()) > 0
