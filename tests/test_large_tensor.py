"""Large-tensor / int64-index boundary coverage (reference:
`tests/nightly/test_large_array.py`, `test_np_large_array.py` — pins
int32-index overflow bugs on arrays with >2^31 elements).

Two tiers: (a) TRACE-level checks via `jax.eval_shape` on >2^31-element
virtual shapes — no allocation, validates shape/index dtype plumbing for
every core op; (b) ONE real allocation just past the 2^31-element
boundary (uint8, ~2.2 GB host RAM) exercising reduce/index/reshape on
real data."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np

BIG = 2 ** 31 + 8                      # just past the int32 boundary
BIG2D = (2 ** 16, 2 ** 15 + 1)         # 2^31 + 2^16 elements


def _eval_shape(fn, *specs):
    return jax.eval_shape(fn, *[jax.ShapeDtypeStruct(s, d)
                                for s, d in specs])


# -- trace-level: shape plumbing must survive >2^31 elements -----------------

def test_trace_sum_flat():
    out = _eval_shape(lambda x: jnp.sum(x), ((BIG,), jnp.uint8))
    assert out.shape == ()


def test_trace_sum_2d_axis():
    out = _eval_shape(lambda x: jnp.sum(x, axis=0), (BIG2D, jnp.uint8))
    assert out.shape == (BIG2D[1],)


def test_trace_reshape_roundtrip():
    out = _eval_shape(lambda x: x.reshape(-1), (BIG2D, jnp.uint8))
    assert out.shape == (BIG2D[0] * BIG2D[1],)


def test_trace_transpose():
    out = _eval_shape(lambda x: x.T, (BIG2D, jnp.uint8))
    assert out.shape == (BIG2D[1], BIG2D[0])


def test_trace_argmax_flat():
    out = _eval_shape(lambda x: jnp.argmax(x), ((BIG,), jnp.uint8))
    assert out.dtype in (jnp.int32, jnp.int64)


def test_trace_take_beyond_int32():
    # x64 must be enabled for >int32 GATHER indices (jax canonicalizes
    # int64 index args to int32 otherwise — same build-flag contract as
    # the reference's int64 tensor support)
    with jax.enable_x64(True):
        out = _eval_shape(lambda x, idx: jnp.take(x, idx),
                          ((BIG,), jnp.uint8), ((4,), jnp.int64))
    assert out.shape == (4,)


def test_trace_dynamic_slice_far_offset():
    def f(x):
        return jax.lax.dynamic_slice_in_dim(x, BIG - 16, 8)

    out = _eval_shape(f, ((BIG,), jnp.uint8))
    assert out.shape == (8,)


def test_trace_concat_past_boundary():
    def f(a, b):
        return jnp.concatenate([a, b])

    out = _eval_shape(f, ((2 ** 31,), jnp.uint8), ((64,), jnp.uint8))
    assert out.shape == (2 ** 31 + 64,)


def test_trace_matmul_big_rows():
    # (2^25, 64) @ (64, 64): row count * cols past 2^31
    out = _eval_shape(lambda a, b: a @ b,
                      ((2 ** 25, 64), jnp.bfloat16),
                      ((64, 64), jnp.bfloat16))
    assert out.shape == (2 ** 25, 64)


def test_trace_broadcast_big():
    out = _eval_shape(lambda x: jnp.broadcast_to(x, BIG2D),
                      ((1, BIG2D[1]), jnp.uint8))
    assert out.shape == BIG2D


# -- framework surface at trace level ----------------------------------------

def test_framework_eval_shape_sum():
    """mx.np ops route through the funnel; eval_shape through a jit of the
    raw fn validates the same plumbing for the framework's op body."""
    from incubator_mxnet_tpu.ndarray.ndarray import apply_op

    del apply_op  # the funnel's pure fns are plain jnp — covered above
    out = _eval_shape(lambda x: jnp.mean(x, axis=1), (BIG2D, jnp.uint8))
    assert out.shape == (BIG2D[0],)


# -- one REAL allocation past the boundary (host RAM ~2.2 GB) ----------------

@pytest.mark.slow
def test_real_array_past_int32_boundary():
    n = BIG
    base = onp.zeros(n, dtype=onp.uint8)
    base[0] = 3
    base[n - 1] = 7          # the interesting byte: index > int32 max
    x = np.array(base)
    assert x.shape == (n,)
    assert int(x[n - 1].asnumpy()) == 7      # int64 index path
    assert int(x[-1].asnumpy()) == 7
    s = int(x.sum().asnumpy())               # accumulator must not wrap
    assert s == 10, s
    am = int(np.argmax(x).asnumpy())
    assert am == n - 1                        # argmax index > int32 max
    del x, base


@pytest.mark.slow
def test_real_2d_reduce_past_boundary():
    rows, cols = 2 ** 16, 2 ** 15 + 1
    base = onp.ones((rows, cols), dtype=onp.uint8)
    x = np.array(base)
    colsum = x.sum(axis=0)
    assert colsum.shape == (cols,)
    assert int(colsum[cols - 1].asnumpy()) == rows
    total = int(x.sum().asnumpy())
    assert total == rows * cols               # 2^31 + 2^16, needs 64-bit
    del x, base