"""NDArray semantics tests (modeled on the reference's
tests/python/unittest/test_ndarray.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = np.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == onp.float32
    b = np.ones((2,), dtype="int32")
    assert b.dtype == onp.int32
    c = np.array([[1, 2], [3, 4]], dtype="float64")
    assert c.shape == (2, 2)
    d = np.full((2, 2), 7.0)
    assert d.asnumpy().tolist() == [[7.0, 7.0], [7.0, 7.0]]
    e = np.arange(10)
    assert e.size == 10
    f = np.eye(3)
    assert f.asnumpy()[1, 1] == 1.0


def test_arithmetic():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    b = np.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal((a + b).asnumpy(), onp.array([[6, 8], [10, 12]]))
    assert_almost_equal((a - b).asnumpy(), onp.array([[-4, -4], [-4, -4]]))
    assert_almost_equal((a * b).asnumpy(), onp.array([[5, 12], [21, 32]]))
    assert_almost_equal((b / a).asnumpy(), onp.array([[5, 3], [7 / 3, 2]]),
                        rtol=1e-6)
    assert_almost_equal((a ** 2).asnumpy(), onp.array([[1, 4], [9, 16]]))
    assert_almost_equal((2 + a).asnumpy(), onp.array([[3, 4], [5, 6]]))
    assert_almost_equal((2 - a).asnumpy(), onp.array([[1, 0], [-1, -2]]))
    assert_almost_equal((-a).asnumpy(), -onp.array([[1.0, 2], [3, 4]]))
    assert_almost_equal((a @ b).asnumpy(),
                        onp.array([[1.0, 2], [3, 4]]) @ onp.array([[5.0, 6], [7, 8]]))


def test_inplace_mutation_versioning():
    a = np.array([1.0, 2.0, 3.0])
    v0 = a.version
    a += 1
    assert a.version > v0
    assert_almost_equal(a.asnumpy(), onp.array([2.0, 3.0, 4.0]))
    a *= 2
    assert_almost_equal(a.asnumpy(), onp.array([4.0, 6.0, 8.0]))
    a[1] = 100.0
    assert_almost_equal(a.asnumpy(), onp.array([4.0, 100.0, 8.0]))
    a[:] = 0.0
    assert_almost_equal(a.asnumpy(), onp.zeros(3))


def test_indexing():
    a = np.arange(24).reshape(2, 3, 4)
    assert a[1, 2, 3].item() == 23
    assert a[0].shape == (3, 4)
    assert a[:, 1].shape == (2, 4)
    assert a[..., -1].shape == (2, 3)
    assert a[0, ::2].shape == (2, 4)
    # boolean mask
    b = np.array([1.0, -2.0, 3.0])
    assert (b[b > 0]).shape == (2,)
    # integer array indexing
    idx = np.array([0, 2], dtype="int32")
    assert_almost_equal(b[idx].asnumpy(), onp.array([1.0, 3.0]))


def test_reshape_transpose():
    a = np.arange(12).reshape(3, 4)
    assert a.T.shape == (4, 3)
    assert a.reshape(2, 6).shape == (2, 6)
    assert a.reshape(-1).shape == (12,)
    assert a.transpose(1, 0).shape == (4, 3)
    assert a.flatten().shape == (3, 4)
    assert np.expand_dims(a, 0).shape == (1, 3, 4)
    assert a.squeeze().shape == (3, 4)


def test_reductions():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().item() == 10.0
    assert a.mean().item() == 2.5
    assert a.max().item() == 4.0
    assert a.min().item() == 1.0
    assert_almost_equal(a.sum(axis=0).asnumpy(), onp.array([4.0, 6.0]))
    assert a.sum(axis=1, keepdims=True).shape == (2, 1)
    assert a.argmax().item() == 3
    assert a.prod().item() == 24.0


def test_astype_copy():
    a = np.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == onp.int32
    c = a.copy()
    c += 1
    assert a.asnumpy()[0] == 1.5
    d = a.astype("float16")
    assert d.dtype == onp.float16


def test_conversion_protocols():
    a = np.array([[1.0, 2.0]])
    assert isinstance(a.asnumpy(), onp.ndarray)
    assert a.tolist() == [[1.0, 2.0]]
    s = np.array([3.5])
    assert float(s) == 3.5
    assert s.asscalar() == 3.5
    with pytest.raises(ValueError):
        a.asscalar()
    assert len(a) == 1
    assert onp.asarray(a).shape == (1, 2)


def test_wait_and_async():
    a = np.ones((64, 64))
    for _ in range(10):
        a = a @ a * 0.01
    a.wait_to_read()  # must not raise
    mx.waitall()


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.params")
    a = np.array([1.0, 2.0])
    b = np.arange(6).reshape(2, 3)
    mx.nd.save(fname, {"a": a, "b": b})
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"a", "b"}
    assert_almost_equal(loaded["a"].asnumpy(), a.asnumpy())
    fname2 = str(tmp_path / "list.params")
    mx.nd.save(fname2, [a, b])
    lst = mx.nd.load(fname2)
    assert len(lst) == 2
    assert_almost_equal(lst[1].asnumpy(), b.asnumpy())


def test_device_placement():
    a = np.ones((2, 2), device=mx.cpu())
    assert a.device.device_type == "cpu"
    b = a.to_device(mx.cpu(0))
    assert b.shape == (2, 2)


def test_comparisons():
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([2.0, 2.0, 2.0])
    assert (a == b).asnumpy().tolist() == [False, True, False]
    assert (a < b).asnumpy().tolist() == [True, False, False]
    assert (a >= 2).asnumpy().tolist() == [False, True, True]


def test_legacy_nd_namespace():
    a = mx.nd.zeros((2, 2))
    assert a.shape == (2, 2)
    b = mx.nd.dot(np.ones((2, 3)), np.ones((3, 4)))
    assert b.shape == (2, 4)
    assert_almost_equal(b.asnumpy(), onp.full((2, 4), 3.0))
