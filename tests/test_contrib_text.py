"""contrib.text / contrib.tensorboard tests (reference model:
tests/python/unittest/test_contrib_text.py)."""
import collections
import json

import numpy as onp
import pytest

from incubator_mxnet_tpu import contrib


def _write_emb(path, rows, delim=" "):
    with open(path, "w") as f:
        for tok, vec in rows:
            f.write(tok + delim + delim.join(str(v) for v in vec) + "\n")


def test_count_tokens_from_str():
    c = contrib.text.utils.count_tokens_from_str("a b b\nc a A", to_lower=True)
    assert c == collections.Counter({"a": 3, "b": 2, "c": 1})


def test_vocabulary_ordering_and_limits():
    counter = collections.Counter({"the": 10, "cat": 5, "sat": 5, "rare": 1})
    v = contrib.text.Vocabulary(counter, most_freq_count=2, min_freq=2,
                                reserved_tokens=["<pad>"])
    assert v.idx_to_token[0] == "<unk>"
    assert v.idx_to_token[1] == "<pad>"
    assert len(v) == 4  # unk, pad + 2 most frequent
    assert v.to_indices("the") == 2
    assert v.to_indices("nope") == 0
    assert v.to_tokens([0, 1]) == ["<unk>", "<pad>"]
    with pytest.raises(ValueError):
        v.to_tokens(99)


def test_vocabulary_rejects_bad_reserved():
    with pytest.raises(ValueError):
        contrib.text.Vocabulary(unknown_token="<unk>",
                                reserved_tokens=["<unk>"])
    with pytest.raises(ValueError):
        contrib.text.Vocabulary(reserved_tokens=["<pad>", "<pad>"])


def test_custom_embedding_loads_file(tmp_path):
    p = str(tmp_path / "emb.txt")
    _write_emb(p, [("cat", [1.0, 2.0]), ("dog", [3.0, 4.0]),
                   ("cat", [9.0, 9.0])])  # duplicate: first wins
    emb = contrib.text.embedding.CustomEmbedding(p)
    assert emb.vec_len == 2
    onp.testing.assert_allclose(
        emb.get_vecs_by_tokens("cat").asnumpy(), [1.0, 2.0])
    onp.testing.assert_allclose(
        emb.get_vecs_by_tokens(["dog", "unknown"]).asnumpy(),
        [[3.0, 4.0], [0.0, 0.0]])


def test_embedding_with_vocabulary(tmp_path):
    p = str(tmp_path / "emb.txt")
    _write_emb(p, [("cat", [1.0, 2.0]), ("dog", [3.0, 4.0])])
    counter = collections.Counter({"cat": 3, "bird": 2})
    voc = contrib.text.Vocabulary(counter)
    emb = contrib.text.embedding.CustomEmbedding(p, vocabulary=voc)
    assert len(emb) == len(voc)
    onp.testing.assert_allclose(
        emb.get_vecs_by_tokens("bird").asnumpy(), [0.0, 0.0])  # no vector
    onp.testing.assert_allclose(
        emb.get_vecs_by_tokens("cat").asnumpy(), [1.0, 2.0])


def test_update_token_vectors(tmp_path):
    p = str(tmp_path / "emb.txt")
    _write_emb(p, [("cat", [1.0, 2.0])])
    emb = contrib.text.embedding.CustomEmbedding(p)
    emb.update_token_vectors("cat", onp.array([[5.0, 6.0]], onp.float32))
    onp.testing.assert_allclose(
        emb.get_vecs_by_tokens("cat").asnumpy(), [5.0, 6.0])
    with pytest.raises(ValueError):
        emb.update_token_vectors("nope", onp.zeros((1, 2), onp.float32))


def test_composite_embedding(tmp_path):
    p1, p2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    _write_emb(p1, [("cat", [1.0])])
    _write_emb(p2, [("cat", [2.0, 3.0])])
    voc = contrib.text.Vocabulary(collections.Counter({"cat": 1}))
    comp = contrib.text.embedding.CompositeEmbedding(
        voc, [contrib.text.embedding.CustomEmbedding(p1),
              contrib.text.embedding.CustomEmbedding(p2)])
    assert comp.vec_len == 3
    onp.testing.assert_allclose(
        comp.get_vecs_by_tokens("cat").asnumpy(), [1.0, 2.0, 3.0])


def test_registry_create_and_missing_file():
    with pytest.raises(FileNotFoundError, match="network"):
        contrib.text.embedding.create("glove",
                                      pretrained_file_path="/no/such/file")
    with pytest.raises(KeyError):
        contrib.text.embedding.create("nope")
    assert "glove" in contrib.text.embedding.get_pretrained_file_names()


def test_tensorboard_callback_jsonl(tmp_path):
    import types

    from incubator_mxnet_tpu import gluon

    m = gluon.metric.Accuracy()
    from incubator_mxnet_tpu import np as mnp

    m.update(mnp.array([0, 1]), mnp.array([[0.9, 0.1], [0.1, 0.9]]))
    cb = contrib.tensorboard.LogMetricsCallback(str(tmp_path / "tb"))
    cb(types.SimpleNamespace(eval_metric=m))
    if isinstance(cb.summary_writer, contrib.tensorboard._JsonlWriter):
        events = [json.loads(line) for line in
                  open(tmp_path / "tb" / "metrics.jsonl")]
        assert events and events[0]["value"] == 1.0
    else:  # real SummaryWriter available (torch tensorboard)
        cb.summary_writer.close()
        import os

        assert any(f.startswith("events") for f in
                   os.listdir(tmp_path / "tb"))


def test_contrib_shim_modules():
    assert contrib.io is not None
    assert contrib.ndarray is not None
    assert contrib.symbol is not None
