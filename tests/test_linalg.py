"""linalg tests: np.linalg wrappers + the reference linalg_* op family with
finite-difference gradient checks (reference:
`tests/python/unittest/test_operator.py` test_laop_* suites)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.numpy import linalg as la
from incubator_mxnet_tpu.test_utils import check_numeric_gradient

RNG = onp.random.RandomState(42)


def _spd(n, jitter=3.0):
    a = RNG.randn(n, n).astype("float32")
    return np.array(a @ a.T + jitter * onp.eye(n, dtype="float32"))


def _mat(*shape):
    return np.array(RNG.randn(*shape).astype("float32"))


def test_gemm2():
    A, B = _mat(3, 4), _mat(4, 5)
    out = la.gemm2(A, B, alpha=2.0)
    onp.testing.assert_allclose(out.asnumpy(),
                                2.0 * A.asnumpy() @ B.asnumpy(),
                                rtol=1e-4, atol=1e-5)
    out_t = la.gemm2(A, A, transpose_b=True)
    onp.testing.assert_allclose(out_t.asnumpy(),
                                A.asnumpy() @ A.asnumpy().T,
                                rtol=1e-4, atol=1e-5)


def test_potrf_potri():
    S = _spd(4)
    L = la.potrf(S)
    onp.testing.assert_allclose((L @ L.T).asnumpy(), S.asnumpy(),
                                rtol=1e-4, atol=1e-4)
    U = la.potrf(S, lower=False)
    onp.testing.assert_allclose(U.asnumpy(), L.asnumpy().T,
                                rtol=1e-5, atol=1e-6)
    Sinv = la.potri(L)
    onp.testing.assert_allclose((Sinv @ S).asnumpy(), onp.eye(4),
                                atol=2e-3)


def test_trsm_trmm():
    S = _spd(4)
    L = la.potrf(S)
    B = _mat(4, 3)
    X = la.trsm(L, B, alpha=2.0)
    onp.testing.assert_allclose((L @ X).asnumpy(), 2.0 * B.asnumpy(),
                                rtol=1e-4, atol=1e-4)
    Br = _mat(3, 4)
    Xr = la.trsm(L, Br, rightside=True)
    onp.testing.assert_allclose((Xr @ L).asnumpy(), Br.asnumpy(),
                                rtol=1e-4, atol=1e-4)
    M = la.trmm(L, B)
    onp.testing.assert_allclose(M.asnumpy(),
                                onp.tril(L.asnumpy()) @ B.asnumpy(),
                                rtol=1e-4, atol=1e-4)


def test_syrk_sumlogdiag_diag_trian():
    A = _mat(3, 5)
    onp.testing.assert_allclose(la.syrk(A).asnumpy(),
                                A.asnumpy() @ A.asnumpy().T,
                                rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(la.syrk(A, transpose=True).asnumpy(),
                                A.asnumpy().T @ A.asnumpy(),
                                rtol=1e-4, atol=1e-4)
    S = _spd(4)
    L = la.potrf(S)
    onp.testing.assert_allclose(
        float(la.sumlogdiag(L).item()),
        float(onp.log(onp.diag(L.asnumpy())).sum()), rtol=1e-5)
    d = la.extractdiag(S)
    onp.testing.assert_allclose(d.asnumpy(), onp.diag(S.asnumpy()))
    D = la.makediag(d)
    onp.testing.assert_allclose(D.asnumpy(), onp.diag(onp.diag(S.asnumpy())))
    v = la.extracttrian(S)
    back = la.maketrian(v)
    onp.testing.assert_allclose(back.asnumpy(), onp.tril(S.asnumpy()))


def test_gelqf():
    A = _mat(3, 5)
    L, Q = la.gelqf(A)
    onp.testing.assert_allclose((L @ Q).asnumpy(), A.asnumpy(),
                                rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose((Q @ Q.T).asnumpy(), onp.eye(3), atol=1e-5)


def test_np_linalg_wrappers():
    S = _spd(3)
    onp.testing.assert_allclose(la.inv(S).asnumpy(),
                                onp.linalg.inv(S.asnumpy()),
                                rtol=1e-3, atol=1e-4)
    sign, logdet = la.slogdet(S)
    s_ref, l_ref = onp.linalg.slogdet(S.asnumpy())
    assert float(sign.item()) == pytest.approx(float(s_ref))
    assert float(logdet.item()) == pytest.approx(float(l_ref), rel=1e-4)
    b = _mat(3, 2)
    x = la.solve(S, b)
    onp.testing.assert_allclose((S @ x).asnumpy(), b.asnumpy(),
                                rtol=1e-3, atol=1e-3)
    w = la.eigvalsh(S)
    onp.testing.assert_allclose(onp.sort(w.asnumpy()),
                                onp.sort(onp.linalg.eigvalsh(S.asnumpy())),
                                rtol=1e-4, atol=1e-4)


# -- gradient checks ----------------------------------------------------------

def test_grad_gemm2():
    check_numeric_gradient(
        lambda a, b: la.gemm2(a, b).sum(), [_mat(3, 4), _mat(4, 2)])


def test_grad_potrf_sumlogdiag():
    # d/dA sum(log(diag(chol(A)))) = 0.5 inv(A) for SPD A
    check_numeric_gradient(
        lambda a: la.sumlogdiag(la.potrf(a)).sum(), [_spd(3)],
        rtol=3e-2, atol=1e-3)


def test_grad_trsm():
    S = _spd(3)
    L = la.potrf(S)
    check_numeric_gradient(
        lambda b: (la.trsm(L, b) ** 2).sum(), [_mat(3, 2)])


def test_grad_solve():
    check_numeric_gradient(
        lambda a, b: (la.solve(a, b) ** 2).sum(), [_spd(3), _mat(3, 2)],
        rtol=3e-2, atol=1e-3)


def test_grad_inverse_det():
    check_numeric_gradient(
        lambda a: la.inverse(a).sum(), [_spd(3)], rtol=3e-2, atol=1e-3)
    check_numeric_gradient(
        lambda a: la.slogdet(a)[1].sum(), [_spd(3)], rtol=3e-2, atol=1e-3)


def test_grad_norm():
    check_numeric_gradient(lambda a: la.norm(a).sum(), [_mat(4, 3)])
