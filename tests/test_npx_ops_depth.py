"""npx extension-op depth: activations, softmax family, norm ops,
convolution/pooling parameterizations, sequence ops — golden values and
grads (reference: `src/operator/nn/` + npx blocks of test_numpy_op.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np, npx

RNG = onp.random.RandomState(29)


def _x(*shape):
    return np.array(RNG.uniform(-2, 2, shape).astype("float32"))


# -- activation family -------------------------------------------------------

def test_activation_relu_golden():
    x = _x(3, 4)
    onp.testing.assert_allclose(
        npx.activation(x, act_type="relu").asnumpy(),
        onp.maximum(x.asnumpy(), 0), rtol=1e-6)


def test_activation_sigmoid_golden():
    x = _x(3, 4)
    onp.testing.assert_allclose(
        npx.activation(x, act_type="sigmoid").asnumpy(),
        1 / (1 + onp.exp(-x.asnumpy())), rtol=1e-5)


def test_activation_softsign():
    x = _x(3, 4)
    onp.testing.assert_allclose(
        npx.activation(x, act_type="softsign").asnumpy(),
        x.asnumpy() / (1 + onp.abs(x.asnumpy())), rtol=1e-5)


def test_leaky_relu_modes():
    x = _x(4, 4)
    got = npx.leaky_relu(x, act_type="leaky", slope=0.2).asnumpy()
    xv = x.asnumpy()
    onp.testing.assert_allclose(got, onp.where(xv > 0, xv, 0.2 * xv),
                                rtol=1e-5)


def test_leaky_relu_elu():
    x = _x(4, 4)
    got = npx.leaky_relu(x, act_type="elu", slope=1.0).asnumpy()
    xv = x.asnumpy()
    onp.testing.assert_allclose(got, onp.where(xv > 0, xv,
                                               onp.expm1(xv)), rtol=1e-4,
                                atol=1e-5)


def test_gelu_exact_vs_tanh():
    x = _x(4, 4)
    a = npx.gelu(x, approximate=True).asnumpy()
    b = npx.gelu(x, approximate=False).asnumpy()
    onp.testing.assert_allclose(a, b, atol=5e-3)
    assert not onp.array_equal(a, b)


def test_relu_grad_mask():
    x = np.array(onp.array([-1.0, 2.0], "float32"))
    x.attach_grad()
    with autograd.record():
        y = npx.relu(x)
    y.backward()
    onp.testing.assert_array_equal(x.grad.asnumpy(), [0.0, 1.0])


# -- softmax family ----------------------------------------------------------

def test_softmax_rows_sum_to_one():
    x = _x(5, 9)
    s = npx.softmax(x, axis=-1).asnumpy()
    onp.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)


def test_softmax_temperature():
    x = _x(2, 6)
    hot = npx.softmax(x, axis=-1, temperature=0.1).asnumpy()
    cold = npx.softmax(x, axis=-1, temperature=10.0).asnumpy()
    assert hot.max() > cold.max()          # low T sharpens


def test_log_softmax_matches_log_of_softmax():
    x = _x(4, 7)
    onp.testing.assert_allclose(
        npx.log_softmax(x, axis=-1).asnumpy(),
        onp.log(npx.softmax(x, axis=-1).asnumpy()), rtol=1e-4, atol=1e-5)


def test_softmin_is_softmax_of_neg():
    x = _x(3, 5)
    onp.testing.assert_allclose(
        npx.softmin(x, axis=-1).asnumpy(),
        npx.softmax(-x, axis=-1).asnumpy(), rtol=1e-5)


def test_masked_softmax_zeroes_masked():
    x = _x(2, 4)
    mask = np.array(onp.array([[1, 1, 0, 0], [1, 0, 1, 0]], "float32"))
    s = npx.masked_softmax(x, mask).asnumpy()
    assert (s[0, 2:] == 0).all() and s[1, 1] == 0 and s[1, 3] == 0
    onp.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)


def test_softmax_grad_is_jacobian_action():
    x = np.array(onp.array([[1.0, 2.0, 3.0]], "float32"))
    x.attach_grad()
    with autograd.record():
        y = npx.softmax(x, axis=-1)[0, 0]
    y.backward()
    s = onp.exp([1.0, 2.0, 3.0])
    s = s / s.sum()
    ref = s[0] * (onp.array([1.0, 0, 0]) - s)
    onp.testing.assert_allclose(x.grad.asnumpy()[0], ref, rtol=1e-4)


# -- norms -------------------------------------------------------------------

def test_batch_norm_inference_formula():
    x = _x(4, 3, 2, 2)
    g = np.array(onp.array([1.0, 2.0, 0.5], "float32"))
    b = np.array(onp.array([0.1, -0.1, 0.0], "float32"))
    mean = np.array(onp.array([0.2, -0.3, 0.0], "float32"))
    var = np.array(onp.array([1.5, 0.5, 2.0], "float32"))
    got = npx.batch_norm(x, g, b, mean, var, eps=1e-3,
                         fix_gamma=False).asnumpy()
    xv = x.asnumpy()
    ref = ((xv - mean.asnumpy()[None, :, None, None])
           / onp.sqrt(var.asnumpy()[None, :, None, None] + 1e-3)
           * g.asnumpy()[None, :, None, None]
           + b.asnumpy()[None, :, None, None])
    onp.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_layer_norm_eps_respected():
    x = np.array(onp.ones((2, 3), "float32"))  # zero variance
    g = np.array(onp.ones((3,), "float32"))
    b = np.array(onp.zeros((3,), "float32"))
    out = npx.layer_norm(x, g, b, eps=1e-2).asnumpy()
    assert onp.isfinite(out).all()


def test_l2_normalization_unit_norm():
    x = _x(4, 6)
    out = npx.l2_normalization(x, mode="instance").asnumpy()
    onp.testing.assert_allclose(onp.linalg.norm(out, axis=1), 1.0,
                                rtol=1e-4)


def test_rms_norm_if_present():
    if not hasattr(npx, "rms_norm"):
        pytest.skip("rms_norm not exposed")
    x = _x(3, 8)
    g = np.array(onp.ones((8,), "float32"))
    out = npx.rms_norm(x, g).asnumpy()
    xv = x.asnumpy()
    ref = xv / onp.sqrt((xv ** 2).mean(-1, keepdims=True) + 1e-5)
    onp.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


# -- convolution parameterizations -------------------------------------------

def test_convolution_1x1_is_channel_mix():
    x = _x(1, 3, 5, 5)
    w = _x(2, 3, 1, 1)
    out = npx.convolution(x, w, None, kernel=(1, 1), num_filter=2,
                          no_bias=True).asnumpy()
    ref = onp.einsum("nchw,kc->nkhw", x.asnumpy(),
                     w.asnumpy()[:, :, 0, 0])
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_convolution_stride_pad():
    x = _x(1, 1, 8, 8)
    w = _x(1, 1, 3, 3)
    out = npx.convolution(x, w, None, kernel=(3, 3), num_filter=1,
                          stride=(2, 2), pad=(1, 1), no_bias=True)
    assert out.shape == (1, 1, 4, 4)


def test_pooling_avg_include_pad_semantics():
    # valid convention (the reference default): floor((3+2*1-2)/2)+1 = 2
    x = np.array(onp.ones((1, 1, 3, 3), "float32"))
    out = npx.pooling(x, kernel=(2, 2), stride=(2, 2), pad=(1, 1),
                      pool_type="avg").asnumpy()
    assert out.shape == (1, 1, 2, 2)
    assert onp.isfinite(out).all()


def test_pooling_global():
    x = _x(2, 3, 6, 6)
    out = npx.pooling(x, global_pool=True, pool_type="max").asnumpy()
    onp.testing.assert_allclose(out[..., 0, 0],
                                x.asnumpy().max(axis=(2, 3)), rtol=1e-6)


# -- sequence ops ------------------------------------------------------------

def test_sequence_last_picks_by_length():
    x = _x(5, 3, 2)          # (T, N, C)
    vl = np.array(onp.array([2, 5, 1], "float32"))
    out = npx.sequence_last(x, vl, use_sequence_length=True).asnumpy()
    xv = x.asnumpy()
    onp.testing.assert_array_equal(out[0], xv[1, 0])
    onp.testing.assert_array_equal(out[1], xv[4, 1])
    onp.testing.assert_array_equal(out[2], xv[0, 2])


def test_sequence_reverse_respects_length():
    x = _x(4, 2, 1)
    vl = np.array(onp.array([2, 4], "float32"))
    out = npx.sequence_reverse(x, vl, use_sequence_length=True).asnumpy()
    xv = x.asnumpy()
    onp.testing.assert_array_equal(out[0, 0], xv[1, 0])
    onp.testing.assert_array_equal(out[1, 0], xv[0, 0])
    onp.testing.assert_array_equal(out[2, 0], xv[2, 0])  # beyond len: kept
    onp.testing.assert_array_equal(out[0, 1], xv[3, 1])


# -- misc npx ----------------------------------------------------------------

def test_reshape_like():
    a = _x(6, 2)
    b = _x(3, 4)
    assert npx.reshape_like(a, b).shape == (3, 4)


def test_slice_like():
    a = _x(5, 6)
    b = _x(3, 4)
    out = npx.slice_like(a, b)
    assert out.shape == (3, 4)
    onp.testing.assert_array_equal(out.asnumpy(), a.asnumpy()[:3, :4])


def test_broadcast_like():
    a = _x(1, 4)
    b = _x(3, 4)
    assert npx.broadcast_like(a, b).shape == (3, 4)


def test_cast_dtype():
    x = _x(2, 2)
    assert "float16" in str(npx.cast(x, dtype="float16").dtype)


def test_fully_connected_golden():
    x = _x(3, 5)
    w = _x(4, 5)
    b = _x(4)
    out = npx.fully_connected(x, w, b, num_hidden=4).asnumpy()
    onp.testing.assert_allclose(
        out, x.asnumpy() @ w.asnumpy().T + b.asnumpy(), rtol=1e-5)


def test_embedding_grad_is_row_scatter():
    w = _x(6, 3)
    w.attach_grad()
    idx = np.array(onp.array([1, 1, 4], "float32"))
    with autograd.record():
        y = npx.embedding(idx, w, input_dim=6, output_dim=3)
    y.backward()
    g = w.grad.asnumpy()
    onp.testing.assert_allclose(g[1], 2.0, rtol=1e-6)
    onp.testing.assert_allclose(g[4], 1.0, rtol=1e-6)
    assert (g[[0, 2, 3, 5]] == 0).all()


def test_topk_indices_and_both():
    x = np.array(onp.array([[3.0, 1.0, 4.0, 1.0, 5.0]], "float32"))
    idx = npx.topk(x, k=2, ret_typ="indices", axis=-1).asnumpy()
    onp.testing.assert_array_equal(idx[0], [4, 2])
    both = npx.topk(x, k=2, ret_typ="both", axis=-1)
    onp.testing.assert_allclose(both[0].asnumpy()[0], [5.0, 4.0])


def test_arange_like():
    x = _x(4, 7)
    out = npx.arange_like(x, axis=1).asnumpy()
    onp.testing.assert_array_equal(out, onp.arange(7, dtype="float32"))


def test_shape_array():
    x = _x(3, 5)
    onp.testing.assert_array_equal(npx.shape_array(x).asnumpy(), [3, 5])


def test_stop_gradient_blocks():
    x = _x(2, 2)
    x.attach_grad()
    with autograd.record():
        y = (npx.stop_gradient(x) * x).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), x.asnumpy(), rtol=1e-6)