"""Real-accelerator consistency gate (reference discipline:
`check_consistency` cpu-vs-gpu, test_utils.py:1491 / SURVEY §4).

The regular suite runs entirely on a virtual CPU mesh, so TPU-only
numerics (bf16 matmul defaults, pallas non-interpret kernels, int8 MXU
paths) are otherwise exercised only by the bench. This file compares a
core-op sample between the CPU backend and the REAL chip in one
process.

Run on the bench host:  MX_TPU_TESTS=1 python -m pytest
tests/test_tpu_consistency.py -q     (conftest keeps the accelerator
platform visible alongside cpu when MX_TPU_TESTS=1; without it, every
test here skips.)
"""
import os

import numpy as onp
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("MX_TPU_TESTS") != "1",
    reason="real-TPU consistency gate (set MX_TPU_TESTS=1 on a chip host)")


def _accel_device():
    import jax

    import incubator_mxnet_tpu as mx

    if not any(d.platform != "cpu" for d in jax.devices()):
        pytest.skip("no accelerator platform visible")
    return mx.tpu(0)    # maps to the first non-cpu platform


def _pair(fn, inputs, rtol=2e-2, atol=5e-2):
    """check_consistency cpu-vs-accelerator. Tolerances follow the
    reference's fp16 row (test_utils.py:1491 uses rtol=1e-2, atol=1e-1
    for fp16-vs-fp32): TPU matmuls default to bf16 MXU accumulation, so
    near-zero entries of an O(N)-term contraction carry absolute error
    ~1e-2 that no rtol can absorb."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.test_utils import check_consistency

    check_consistency(fn, inputs, devices=[mx.cpu(0), _accel_device()],
                      rtol=rtol, atol=atol)


def _r(*shape, seed=0):
    from incubator_mxnet_tpu import np

    return np.array(onp.random.RandomState(seed)
                    .uniform(-1, 1, shape).astype("float32"))


def test_dot_consistency():
    from incubator_mxnet_tpu import np

    _pair(lambda a, b: np.dot(a, b), [_r(64, 64), _r(64, 64, seed=1)])


def test_conv_bn_relu_consistency():
    from incubator_mxnet_tpu import np, npx

    x = _r(2, 3, 16, 16)
    w = _r(8, 3, 3, 3, seed=1)
    gamma, beta = np.ones((8,)), np.zeros((8,))
    rm, rv = np.zeros((8,)), np.ones((8,))

    def f(x, w, gamma, beta, rm, rv):
        y = npx.convolution(x, w, kernel=(3, 3), num_filter=8,
                            pad=(1, 1), no_bias=True)
        return npx.relu(npx.batch_norm(y, gamma, beta, rm, rv))

    _pair(f, [x, w, gamma, beta, rm, rv])


def test_softmax_reduction_consistency():
    from incubator_mxnet_tpu import np, npx

    _pair(lambda x: npx.softmax(x, axis=-1).sum(axis=0), [_r(32, 128)])


def test_flash_attention_consistency():
    """pallas kernel on-chip vs the XLA fallback path on cpu."""
    from incubator_mxnet_tpu import npx

    q = _r(2, 4, 128, 64)
    k = _r(2, 4, 128, 64, seed=1)
    v = _r(2, 4, 128, 64, seed=2)
    _pair(lambda q, k, v: npx.flash_attention(q, k, v, causal=True),
          [q, k, v], rtol=3e-2, atol=3e-3)


def test_train_step_consistency():
    """One fwd+bwd+SGD step of a small MLP lands on the same weights."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, np

    def step(x, y):
        onp.random.seed(0)
        mx.random.seed(0)      # same init draws on both devices
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(16, activation="relu"),
                gluon.nn.Dense(4))
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
        return [p.data() for p in net.collect_params().values()]

    x = _r(8, 12)
    y = mx.np.array(onp.random.RandomState(3)
                    .randint(0, 4, (8,)).astype("int32"))
    _pair(step, [x, y])


def test_fused_residual_ln_consistency():
    """ops/fused_block.py kernel on-chip vs the composed cpu path (p=0:
    the dropout mask is generator-specific, so the deterministic part of
    the contract is what cross-device consistency can pin)."""
    from incubator_mxnet_tpu import npx

    x = _r(2, 64, 256)
    h = _r(2, 64, 256, seed=1)
    g = _r(256, seed=2)
    b = _r(256, seed=3)
    _pair(lambda x, h, g, b: npx.residual_dropout_ln(x, h, g, b, p=0.0),
          [x, h, g, b], rtol=1e-2, atol=1e-2)


def test_fused_layer_norm_consistency():
    """ops/layer_norm.py kernel on-chip vs the XLA lowering on cpu."""
    from incubator_mxnet_tpu import npx

    x = _r(4, 32, 384)
    g = _r(384, seed=1)
    b = _r(384, seed=2)
    _pair(lambda x, g, b: npx.layer_norm(x, g, b), [x, g, b],
          rtol=1e-2, atol=1e-2)
