"""mx.operator CustomOp/CustomOpProp tests (reference:
`tests/python/unittest/test_operator.py` test_custom_op)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import autograd, gluon, np, operator


@operator.register("scale2")
class Scale2Prop(operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Scale2()


class Scale2(operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] * 2.0)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0] * 2.0)


@operator.register("splitsum")
class SplitSumProp(operator.CustomOpProp):
    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["sum", "diff"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return SplitSum()


class SplitSum(operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        a, b = in_data
        self.assign(out_data[0], req[0], a + b)
        self.assign(out_data[1], req[1], a - b)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        gs, gd = out_grad
        self.assign(in_grad[0], req[0], gs + gd)
        self.assign(in_grad[1], req[1], gs - gd)


def test_custom_forward():
    x = np.array(onp.arange(6, dtype="float32").reshape(2, 3))
    y = operator.Custom(x, op_type="scale2")
    onp.testing.assert_allclose(y.asnumpy(), 2 * x.asnumpy())


def test_custom_backward():
    x = np.array(onp.arange(6, dtype="float32").reshape(2, 3))
    x.attach_grad()
    with autograd.record():
        y = operator.Custom(x, op_type="scale2")
        loss = (y * y).sum()
    loss.backward()
    # d/dx sum((2x)^2) = 8x
    onp.testing.assert_allclose(x.grad.asnumpy(), 8 * x.asnumpy(),
                                rtol=1e-5)


def test_custom_multi_io():
    a = np.array(onp.array([1.0, 2.0], "float32"))
    b = np.array(onp.array([0.5, 1.0], "float32"))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        s, d = operator.Custom(a, b, op_type="splitsum")
        loss = (s * s).sum() + d.sum()
    loss.backward()
    onp.testing.assert_allclose(s.asnumpy(), [1.5, 3.0])
    onp.testing.assert_allclose(d.asnumpy(), [0.5, 1.0])
    # dL/da = 2s + 1; dL/db = 2s - 1
    onp.testing.assert_allclose(a.grad.asnumpy(), 2 * s.asnumpy() + 1)
    onp.testing.assert_allclose(b.grad.asnumpy(), 2 * s.asnumpy() - 1)


def test_custom_in_gluon_net():
    from incubator_mxnet_tpu.gluon.block import Block

    class CustomNet(Block):
        def __init__(self):
            super().__init__()
            self.dense = gluon.nn.Dense(4)

        def forward(self, x):
            return operator.Custom(self.dense(x), op_type="scale2")

    net = CustomNet()
    net.initialize()
    x = np.random.uniform(size=(2, 3))
    with autograd.record():
        out = net(x).sum()
    out.backward()
    g = net.dense.weight.data()._grad
    assert g is not None
    assert onp.abs(g.asnumpy()).sum() > 0


def test_custom_unknown_raises():
    with pytest.raises(ValueError, match="not registered"):
        operator.Custom(np.ones((2,)), op_type="nope")


def test_register_requires_prop():
    with pytest.raises(TypeError):
        operator.register("bad")(int)


def test_registry_listing():
    ops = operator.get_all_registered_operators()
    assert "scale2" in ops and "splitsum" in ops
