"""runtime / model / visualization / error / log module tests
(reference models: tests/python/unittest/test_runtime.py, test_viz.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPU")
    assert "TPU" in feats
    assert isinstance(mx.runtime.feature_list(), list)
    with pytest.raises(RuntimeError):
        feats.is_enabled("NO_SUCH_FEATURE")


def test_runtime_features_singleton():
    assert mx.runtime.Features() is mx.runtime.Features()


def test_model_checkpoint_roundtrip(tmp_path):
    a, w = sym.Variable("a"), sym.Variable("w")
    net = sym.dot(a, w)
    arg = {"w": onp.ones((3, 2), onp.float32)}
    aux = {"stat": onp.zeros((2,), onp.float32)}
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 3, net, arg, aux)
    s2, arg2, aux2 = mx.model.load_checkpoint(prefix, 3)
    assert s2.list_arguments() == ["a", "w"]
    onp.testing.assert_array_equal(arg2["w"].asnumpy(), arg["w"])
    onp.testing.assert_array_equal(aux2["stat"].asnumpy(), aux["stat"])


def test_print_summary():
    a, w = sym.Variable("a"), sym.Variable("w")
    out = sym.relu(sym.dot(a, w))
    text = mx.visualization.print_summary(out, shape={"a": (2, 3), "w": (3, 4)})
    assert "Total params: 18" in text
    assert "relu" in text


def test_print_summary_missing_shape_raises():
    a, b = sym.Variable("a"), sym.Variable("b")
    with pytest.raises(ValueError, match="missing shapes"):
        mx.visualization.print_summary(a + b, shape={"a": (2,)})


def test_plot_network_dot_source(tmp_path):
    a = sym.Variable("data")
    w = sym.Variable("fc_weight")
    net = sym.relu(sym.dot(a, w))
    dot = mx.visualization.plot_network(net)
    src = dot.source
    assert "digraph" in src and "data" in src
    assert "fc_weight" not in src  # hide_weights
    f = tmp_path / "net.dot"
    dot.save(str(f))
    assert f.exists()


def test_error_types():
    assert issubclass(mx.error.InternalError, mx.MXNetError)
    with pytest.raises(mx.MXNetError):
        raise mx.error.ValueError("bad")
    with pytest.raises(ValueError):
        raise mx.error.ValueError("also a builtin ValueError")

    @mx.error.register_error("MyError")
    class MyError(mx.MXNetError):
        pass

    assert mx.error._ERROR_REGISTRY["MyError"] is MyError


def test_log_get_logger(tmp_path):
    logger = mx.log.get_logger("mxtest", filename=str(tmp_path / "x.log"),
                               level=mx.log.INFO)
    logger.info("hello %d", 42)
    assert logger is mx.log.get_logger("mxtest")
    text = (tmp_path / "x.log").read_text()
    assert "hello 42" in text
