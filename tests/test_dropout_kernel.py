"""Hardware-RNG dropout kernel (`ops/dropout.py`) + PRNG impl selection
(`random.py`). The kernel runs in pallas interpret mode off-TPU, so its
numerics are pinned here on the CPU mesh (reference dropout semantics:
`src/operator/nn/dropout-inl.h` — scale-at-train-time, zero elsewhere)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np, npx
from incubator_mxnet_tpu.ops import dropout as hw


def test_kernel_mask_and_scale():
    import jax

    key = jax.random.PRNGKey(0)
    x = onp.ones((64, 256), "float32")
    y = onp.asarray(hw.dropout(jax.numpy.asarray(x), key, 0.25))
    kept = y != 0
    # kept values are exactly x/(1-p); drop rate within 4 sigma
    assert onp.allclose(y[kept], 1.0 / 0.75)
    rate = 1 - kept.mean()
    assert abs(rate - 0.25) < 4 * onp.sqrt(0.25 * 0.75 / x.size)


def test_kernel_backward_recomputes_same_mask():
    import jax

    key = jax.random.PRNGKey(3)
    x = jax.numpy.asarray(onp.random.RandomState(0)
                          .randn(32, 128).astype("float32"))
    y, vjp = jax.vjp(lambda a: hw.dropout(a, key, 0.5), x)
    (dx,) = vjp(jax.numpy.ones_like(y))
    # gradient mask must equal the forward mask (recomputed from the seed)
    onp.testing.assert_array_equal(onp.asarray(y) != 0,
                                   onp.asarray(dx) != 0)
    assert onp.allclose(onp.asarray(dx)[onp.asarray(dx) != 0], 2.0)


def test_kernel_deterministic_per_key():
    import jax

    x = jax.numpy.asarray(onp.ones((16, 128), "float32"))
    a = onp.asarray(hw.dropout(x, jax.random.PRNGKey(7), 0.5))
    b = onp.asarray(hw.dropout(x, jax.random.PRNGKey(7), 0.5))
    c = onp.asarray(hw.dropout(x, jax.random.PRNGKey(8), 0.5))
    onp.testing.assert_array_equal(a, b)
    assert not onp.array_equal(a, c)


def test_supports_eligibility():
    import jax.numpy as jnp

    assert hw.supports((64, 768), (), jnp.float32)
    assert hw.supports((64, 768), (), jnp.bfloat16)   # 'V'-kind dtype
    assert not hw.supports((64, 768), (0,), jnp.float32)   # broadcast axes
    assert not hw.supports((10, 7), (), jnp.float32)       # untileable
    assert not hw.supports((64, 768), (), jnp.int32)
    assert not hw.supports((64, 768), (), jnp.float32, p=1.0)  # degenerate p


def test_npx_dropout_still_correct_through_funnel():
    x = np.array(onp.ones((64, 768), "float32"))
    x.attach_grad()
    with autograd.record():
        y = npx.dropout(x, p=0.25)
    y.backward()
    yn = y.asnumpy()
    kept = yn != 0
    assert onp.allclose(yn[kept], 1.0 / 0.75)
    g = x.grad.asnumpy()
    onp.testing.assert_array_equal(g != 0, kept)


def test_seed_epoch_bumps():
    from incubator_mxnet_tpu.random import seed_epoch

    e0 = seed_epoch()
    mx.random.seed(123)
    assert seed_epoch() == e0 + 1


def test_rng_impl_reported():
    # on the CPU test mesh the default is threefry; MXNET_RNG_IMPL overrides
    impl = mx.random.rng_impl()
    assert impl in ("threefry", "rbg", "unsafe_rbg")


def test_reseed_changes_dataparallel_stream():
    """mx.random.seed() AFTER training has started must change the dropout
    stream of a compiled DataParallel step (the base key refreshes on the
    next step)."""
    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, in_units=16, activation="relu"),
            gluon.nn.Dropout(0.5),
            gluon.nn.Dense(4, in_units=32))
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def run_losses(seed):
        mx.random.seed(seed)
        dp = DataParallel(net, loss_fn, optimizer.SGD(learning_rate=0.0))
        rng = onp.random.RandomState(0)
        x = np.array(rng.uniform(-1, 1, (8, 16)).astype("float32"))
        y = np.array(rng.randint(0, 4, (8,)).astype("int32"))
        first = float(dp.step(x, y).asnumpy())
        mx.random.seed(seed + 1)          # reseed mid-training
        second = float(dp.step(x, y).asnumpy())
        return first, second

    f1, s1 = run_losses(11)
    f2, s2 = run_losses(11)
    # same seed => same first-step loss; lr=0 so params don't move
    assert f1 == pytest.approx(f2, rel=1e-6)
    # the reseed must actually change the second step's dropout draw
    # (compare against a run that does NOT reseed)
    mx.random.seed(11)
    dp = DataParallel(net, loss_fn, optimizer.SGD(learning_rate=0.0))
    rng = onp.random.RandomState(0)
    x = np.array(rng.uniform(-1, 1, (8, 16)).astype("float32"))
    y = np.array(rng.randint(0, 4, (8,)).astype("int32"))
    dp.step(x, y)
    second_no_reseed = float(dp.step(x, y).asnumpy())
    assert s1 != pytest.approx(second_no_reseed, rel=1e-9)
