"""Test-harness parity (reference `python/mxnet/test_utils.py`):
check_symbolic_forward (:1194), check_symbolic_backward (:1277) — the
reference's primary per-op correctness instruments — driven through the
Symbol executor exactly like reference op tests do."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import symbol as sym
from incubator_mxnet_tpu.test_utils import (
    check_consistency, check_symbolic_backward, check_symbolic_forward,
)


@pytest.mark.quick
def test_check_symbolic_forward_dot():
    # the reference docstring's own example (test_utils.py:1240)
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    sym_dot = sym.dot(lhs, rhs)
    mat1 = onp.array([[1, 2], [3, 4]], "float32")
    mat2 = onp.array([[5, 6], [7, 8]], "float32")
    expected = onp.array([[19, 22], [43, 50]], "float32")
    outs = check_symbolic_forward(sym_dot, [mat1, mat2], [expected])
    assert len(outs) == 1


def test_check_symbolic_forward_dict_location():
    a = sym.Variable("a")
    out = sym.exp(a)
    x = onp.random.RandomState(0).uniform(-1, 1, (3, 4)).astype("float32")
    check_symbolic_forward(out, {"a": x}, [onp.exp(x)])


def test_check_symbolic_forward_mismatch_raises():
    a = sym.Variable("a")
    out = sym.exp(a)
    x = onp.ones((2, 2), "float32")
    with pytest.raises(AssertionError, match="FORWARD"):
        check_symbolic_forward(out, [x], [onp.zeros((2, 2), "float32")])


def test_check_symbolic_backward_dot():
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    sym_dot = sym.dot(lhs, rhs)
    rng = onp.random.RandomState(0)
    a = rng.uniform(-1, 1, (3, 4)).astype("float32")
    b = rng.uniform(-1, 1, (4, 2)).astype("float32")
    og = rng.uniform(-1, 1, (3, 2)).astype("float32")
    grads = check_symbolic_backward(
        sym_dot, [a, b], [og], [og @ b.T, a.T @ og])
    assert len(grads) == 2


def test_check_symbolic_backward_grad_req_null():
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    prod = lhs * rhs
    rng = onp.random.RandomState(1)
    a = rng.uniform(1, 2, (2, 3)).astype("float32")
    b = rng.uniform(1, 2, (2, 3)).astype("float32")
    og = onp.ones((2, 3), "float32")
    # rhs gradient suppressed: only lhs compared
    check_symbolic_backward(prod, [a, b], [og], {"lhs": b},
                            grad_req={"lhs": "write", "rhs": "null"})


def test_check_symbolic_backward_mismatch_raises():
    a = sym.Variable("a")
    out = a * a
    x = onp.full((2, 2), 3.0, "float32")
    og = onp.ones((2, 2), "float32")
    with pytest.raises(AssertionError, match="BACKWARD"):
        check_symbolic_backward(out, [x], [og],
                                [onp.zeros((2, 2), "float32")])


@pytest.mark.quick
def test_check_consistency_across_virtual_devices():
    """On the CPU test mesh this compares cpu(0) against the default
    device — the same helper the real-chip gate
    (test_tpu_consistency.py) uses against the accelerator."""
    from incubator_mxnet_tpu import np

    x = np.array(onp.random.RandomState(0)
                 .uniform(-1, 1, (8, 8)).astype("float32"))
    check_consistency(lambda a: np.dot(a, a.T), [x],
                      devices=[mx.cpu(0), mx.cpu(0)])
