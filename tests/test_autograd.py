"""Autograd tests (modeled on tests/python/unittest/test_autograd.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np
from incubator_mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_record_flags():
    assert not autograd.is_recording()
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        assert autograd.is_recording()
    assert not autograd.is_recording()
    with autograd.record(train_mode=False):
        assert autograd.is_recording()
        assert not autograd.is_training()


def test_simple_backward():
    x = np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * onp.array([1.0, 2.0, 3.0]))


def test_chain_backward():
    x = np.array([0.5, -1.0])
    x.attach_grad()
    with autograd.record():
        y = np.exp(x)
        z = y * y
        w = z.sum()
    w.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * onp.exp(2 * onp.array([0.5, -1.0])),
                        rtol=1e-5)


def test_branching_accumulation():
    x = np.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3 + x * x  # two paths into x
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), onp.array([3 + 2 * 2.0]))


def test_grad_req_add():
    x = np.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad.asnumpy(), 3 * 2 * onp.array([1.0, 2.0]))


def test_grad_req_null():
    x = np.array([1.0])
    x.attach_grad(grad_req="null")
    with autograd.record():
        y = x * 2
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), onp.zeros(1))


def test_head_grads():
    x = np.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(np.array([10.0, 100.0]))
    assert_almost_equal(x.grad.asnumpy(), onp.array([30.0, 300.0]))


def test_detach():
    x = np.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    # z = const(4) * x → dz/dx = 4
    assert_almost_equal(x.grad.asnumpy(), onp.array([4.0]))


def test_multi_output_ops():
    x = np.arange(6.0).reshape(2, 3)
    x.attach_grad()
    with autograd.record():
        a, b = np.split(x, 2, axis=0) if hasattr(np, "split") else x.split(2)
        y = (a * 2).sum() + (b * 3).sum()
    y.backward()
    expected = onp.concatenate([onp.full((1, 3), 2.0), onp.full((1, 3), 3.0)])
    assert_almost_equal(x.grad.asnumpy(), expected)


def test_backward_through_mutation():
    x = np.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        y += 1  # in-place on recorded array
        z = (y * y).sum()
    z.backward()
    # z = (2x+1)^2 → dz/dx = 2(2x+1)*2
    assert_almost_equal(x.grad.asnumpy(), 4 * (2 * onp.array([1.0, 2.0]) + 1))


def test_autograd_grad_api():
    x = np.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad([y], [x])
    assert_almost_equal(g.asnumpy(), onp.array([27.0]))


def test_higher_order_grad():
    x = np.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        (g,) = autograd.grad([y], [x], create_graph=True)
        z = g.sum()
    z.backward()
    # d2/dx2 x^3 = 6x
    assert_almost_equal(x.grad.asnumpy(), onp.array([12.0]), rtol=1e-5)


def test_custom_function():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = np.array([3.0, 4.0])
    x.attach_grad()
    sq = Square()
    with autograd.record():
        y = sq(x)
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * onp.array([3.0, 4.0]))


def test_numeric_gradient():
    check_numeric_gradient(lambda x: (x * x + 3 * x).sum(),
                           [np.array([0.3, -0.4, 0.9])])


def test_no_record_no_grad():
    x = np.array([1.0])
    x.attach_grad()
    y = x * 5  # not recorded
    with pytest.raises(ValueError):
        y.backward()  # nothing on tape
