"""Telemetry subsystem: registry shard semantics under threads, funnel
stage-trace on/off contract, roofline analyzer on a synthetic trace,
monitor NaN detection (eager + compiled), rank aggregation degenerate
path, and the built-in series wiring (ISSUE 2)."""
import json
import threading
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.telemetry import monitor, registry, roofline, stages


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    stages.disable()
    stages.reset()
    monitor.uninstall_nan_hook()
    monitor.clear_nan_findings()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_counter_histogram_under_threads():
    c = registry.counter("t_reqs_total")
    h = registry.histogram("t_lat_seconds", buckets=(0.1, 1.0))
    base = c.value

    def work():
        for _ in range(1000):
            c.inc()
        for _ in range(100):
            h.observe(0.05)
        h.observe(5.0)           # lands in +inf

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value - base == 8000
    snap = h.snapshot()
    assert snap["count"] == 8 * 101
    assert snap["buckets"][0.1] == 800
    assert snap["inf"] == 8
    assert snap["min"] == 0.05 and snap["max"] == 5.0


def test_registry_report_dump_exposition(tmp_path):
    registry.counter("t_dump_total").inc(3)
    registry.gauge("t_depth").set(7)
    rep = registry.report()
    assert rep["t_dump_total"]["value"] == 3
    assert rep["t_depth"]["value"] == 7
    # built-in series are always present
    assert "mx_step_time_seconds" in rep
    assert "mx_jit_cache_hits_total" in rep          # pull-mode collector
    p = registry.dump(str(tmp_path / "metrics.json"))
    with open(p) as f:
        assert json.load(f)["t_dump_total"]["value"] == 3
    text = registry.exposition()
    assert "# TYPE t_dump_total counter" in text
    assert "t_dump_total 3" in text
    assert "mx_step_time_seconds_bucket" in text     # histogram exposition


def test_registry_labeled_series_and_type_conflict():
    registry.counter("t_labeled_total", labels={"k": "a"}).inc()
    registry.counter("t_labeled_total", labels={"k": "b"}).inc(2)
    rep = registry.report()
    assert rep['t_labeled_total{k="a"}']["value"] == 1
    assert rep['t_labeled_total{k="b"}']["value"] == 2
    with pytest.raises(TypeError):
        registry.gauge("t_labeled_total", labels={"k": "a"})


def test_step_and_examples_series():
    before = registry.EXAMPLES.value
    registry.step(0.05, examples=32)
    assert registry.EXAMPLES.value - before == 32
    assert registry.STEP_TIME.snapshot()["count"] >= 1


# ---------------------------------------------------------------------------
# stage tracing
# ---------------------------------------------------------------------------

def test_stage_trace_records_funnel_stages():
    a = np.array(onp.random.RandomState(0).uniform(-1, 1, (16, 16))
                 .astype("float32"))
    stages.reset()
    stages.enable()
    try:
        for _ in range(5):
            np.dot(a, a).wait_to_read()
    finally:
        stages.disable()
    rep = stages.stage_report()
    for stage in ("prologue", "amp_lookup", "cache_key", "dispatch", "wrap"):
        assert stage in rep, rep.keys()
        assert rep[stage]["count"] >= 5
        assert rep[stage]["mean_us"] >= 0.0
    assert rep["total"]["mean_us"] > 0.0
    assert "| dispatch |" in stages.format_report(rep)


def test_stage_trace_tape_stage_under_recording():
    from incubator_mxnet_tpu import autograd

    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    a.attach_grad()
    stages.reset()
    stages.enable()
    try:
        with autograd.record():
            (np.dot(a, a)).sum().backward()
    finally:
        stages.disable()
    assert "tape" in stages.stage_report()


def test_stage_trace_off_path_no_alloc_and_cheap():
    """MXNET_TELEMETRY=0 contract: the funnel probes are dead branches —
    no allocation attributable to the stages module, and the probe cost
    itself (6 global-load + is-None checks) is <3% of a funnel op."""
    import tracemalloc

    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod

    assert nd_mod._STAGE_HOOK is None          # off by default
    a = np.array(onp.random.RandomState(0).uniform(-1, 1, (16, 16))
                 .astype("float32"))
    np.dot(a, a).wait_to_read()                # warm compile caches
    tracemalloc.start()
    for _ in range(50):
        np.dot(a, a)
    mx.waitall()
    snap = tracemalloc.take_snapshot()
    tracemalloc.stop()
    stage_blocks = [
        s for s in snap.statistics("filename")
        if s.traceback and "telemetry" in str(s.traceback[0].filename)]
    assert not stage_blocks, stage_blocks     # zero telemetry allocations

    # measure one op through the funnel...
    iters = 300
    t0 = time.perf_counter()
    for _ in range(iters):
        np.dot(a, a)
    mx.waitall()
    per_op = (time.perf_counter() - t0) / iters
    # ...and the literal off-path probe pattern, 6 sites per op
    sh = nd_mod._STAGE_HOOK
    t0 = time.perf_counter()
    for _ in range(iters):
        if sh is not None:
            pass
        if sh is not None:
            pass
        if sh is not None:
            pass
        if sh is not None:
            pass
        if sh is not None:
            pass
        if sh is not None:
            pass
    probe_per_op = (time.perf_counter() - t0) / iters
    assert probe_per_op < 0.03 * per_op, (probe_per_op, per_op)


# ---------------------------------------------------------------------------
# roofline analyzer (synthetic chrome-trace fixture)
# ---------------------------------------------------------------------------

def _synthetic_trace():
    """Two device lanes + one host-python lane that must be ignored; dot
    and fusion events carry XPlane byte stats, transpose doesn't."""
    return [
        {"ph": "M", "name": "process_name", "pid": 1001,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 1002,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "process_name", "pid": 5,
         "args": {"name": "python"}},
        # 2 ms of dot moving 2 MiB  -> 1.048576 GB/s
        {"ph": "X", "pid": 1001, "name": "dot.1", "ts": 0, "dur": 1000,
         "args": {"bytes accessed": 2**20}},
        {"ph": "X", "pid": 1001, "name": "dot.2", "ts": 1000, "dur": 1000,
         "args": {"bytes_accessed": 2**20}},
        # 1 ms of fusion moving 4 MiB -> 4.194304 GB/s
        {"ph": "X", "pid": 1002, "name": "loop_add_fusion", "ts": 0,
         "dur": 1000, "args": {"bytes accessed": 4 * 2**20}},
        # copy with no byte stat: time counts, bytes unknown
        {"ph": "X", "pid": 1001, "name": "transpose.3", "ts": 2000,
         "dur": 500},
        # runtime/interpreter noise that must be excluded
        {"ph": "X", "pid": 1002, "name": "$pjit.py:330 cache_miss",
         "ts": 0, "dur": 99999},
        {"ph": "X", "pid": 1002, "name": "ThunkExecutor::Execute",
         "ts": 0, "dur": 99999},
        # event on a non-device lane must be ignored entirely
        {"ph": "X", "pid": 5, "name": "dot_python", "ts": 0, "dur": 12345},
    ]


def test_roofline_analyze_synthetic():
    analysis = roofline.analyze(_synthetic_trace(), peak_gbs=819.0)
    rows = {r["phase"]: r for r in analysis["rows"]}
    mm = rows["matmul/conv"]
    assert mm["events"] == 2 and mm["time_us"] == 2000.0
    assert mm["bytes"] == 2 * 2**20
    assert mm["achieved_gbs"] == pytest.approx(2 * 2**20 / 2e-3 / 1e9)
    assert mm["peak_fraction"] == pytest.approx(mm["achieved_gbs"] / 819.0)
    fu = rows["fusion/elementwise"]
    assert fu["bytes"] == 4 * 2**20
    assert fu["achieved_gbs"] == pytest.approx(4 * 2**20 / 1e-3 / 1e9)
    cp = rows["copy/layout"]
    assert cp["bytes"] == 0 and cp["time_us"] == 500.0
    tot = analysis["total"]
    assert tot["events"] == 4 and tot["time_us"] == 3500.0
    # 3 of 4 kept events had byte stats
    assert analysis["meta"]["bytes_coverage"] == pytest.approx(0.75)
    table = roofline.format_table(analysis)
    assert "matmul/conv" in table and "% of peak" in table


def test_roofline_mem_analysis_and_device_key(tmp_path):
    an = roofline.analyze(
        _synthetic_trace(), device="v5e",
        mem_analysis={"argument_size_in_bytes": 100,
                      "output_size_in_bytes": 50,
                      "temp_size_in_bytes": 25,
                      "alias_size_in_bytes": 0,
                      "generated_code_size_in_bytes": 1})
    assert an["meta"]["peak_gbs"] == roofline.PEAK_HBM_GBS["v5e"]
    assert an["meta"]["program_bytes"] == 175
    p = roofline.write_report(str(tmp_path / "r.md"), an, "synthetic",
                              notes=["a note"])
    text = open(p).read()
    assert "# synthetic" in text and "a note" in text


# ---------------------------------------------------------------------------
# monitor + NaN hook
# ---------------------------------------------------------------------------

def test_monitor_collects_stats_batched():
    m = monitor.Monitor(pattern="dot")
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    m.tic()
    np.dot(a, a)
    (a + 1)                       # must NOT match the pattern
    rows = m.toc()
    assert rows, "no stats collected"
    assert {r[1] for r in rows} == {"dot"}
    stats = {r[2] for r in rows}
    assert {"norm", "mean", "max_abs", "nan", "inf"} <= stats
    nan_rows = [r for r in rows if r[2] == "nan"]
    assert all(r[3] == 0.0 for r in nan_rows)
    # hook uninstalled after toc
    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod

    assert nd_mod._MONITOR_HOOK is None


def test_monitor_interval_skips_cycles():
    m = monitor.Monitor(interval=2, pattern="dot")
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    m.tic(); np.dot(a, a); first = m.toc()     # step 0: active
    m.tic(); np.dot(a, a); second = m.toc()    # step 1: skipped
    assert first and not second


def test_nan_hook_eager_raises_with_op_name():
    monitor.install_nan_hook(mode="raise")
    with pytest.raises(mx.MXNetError, match="log"):
        np.log(np.array([-1.0]))
    monitor.uninstall_nan_hook()
    monitor.clear_nan_findings()


def test_nan_hook_hybridized_jit_positive_and_clean():
    """Acceptance: the Monitor NaN hook catches an injected inf in a
    hybridized block under jit, and a clean run records nothing."""
    from incubator_mxnet_tpu import gluon

    net = gluon.nn.Dense(4, in_units=4)
    net.initialize()
    x_ok = np.array([[1.0, 2.0, 3.0, 4.0]], dtype="float32")
    net(x_ok)
    net.hybridize()
    net(x_ok).wait_to_read()      # eager deferred pass; compile comes next
    monitor.install_nan_hook(mode="warn")
    try:
        net(x_ok).wait_to_read()  # traces WITH the hook -> guard embedded
        mx.waitall()
        assert monitor.nan_findings() == []      # clean path: no findings
        monitor.check()                          # and check() passes
        x_bad = np.array([[float("inf"), 2.0, 3.0, 4.0]], dtype="float32")
        net(x_bad).wait_to_read()
        mx.waitall()
        findings = monitor.nan_findings()
        assert findings, "inf not detected under jit"
        assert any(f["op"] == "fully_connected" and f["where"] == "jit"
                   for f in findings), findings
        with pytest.raises(mx.MXNetError, match="fully_connected"):
            monitor.check()
    finally:
        monitor.uninstall_nan_hook()
        monitor.clear_nan_findings()


# ---------------------------------------------------------------------------
# rank aggregation (degenerate 1-process path)
# ---------------------------------------------------------------------------

def test_rank_aggregation_single_process():
    monitor.queue_rank_stats({"grad_norm": 2.5, "loss": 0.75})
    agg = monitor.sync_rank_stats()
    assert agg["grad_norm"] == {"min": 2.5, "max": 2.5, "mean": 2.5,
                                "ranks": 1}
    assert monitor.rank_aggregate()["loss"]["mean"] == 0.75
    # queue drained: next sync aggregates nothing
    assert monitor.sync_rank_stats() == {}


def test_kvstore_barrier_drains_rank_stats():
    from incubator_mxnet_tpu import kv

    monitor.queue_rank_stats({"step_ms": 12.0})
    store = kv.create("dist_sync")
    store.barrier()               # rides the profiler command channel
    assert monitor.rank_aggregate()["step_ms"]["ranks"] == 1


# ---------------------------------------------------------------------------
# built-in series wiring
# ---------------------------------------------------------------------------

def test_h2d_bytes_counter_counts_host_arrays():
    before = registry.H2D_BYTES.value
    np.array(onp.zeros((64, 64), "float32")).wait_to_read()
    assert registry.H2D_BYTES.value - before >= 64 * 64 * 4


def test_jit_cache_and_compile_series():
    from incubator_mxnet_tpu.ndarray import ndarray as nd_mod
    from incubator_mxnet_tpu.ndarray.ndarray import jit_cache_info

    # earlier suite tests may have deny-listed "dot" (a deliberate
    # bad-shape call trace-fails -> _JIT_DENY) which would starve the
    # hit/miss counters here — clear it so the cacheable path runs
    nd_mod._JIT_DENY.discard("dot")
    nd_mod._JIT_FAILS.pop("dot", None)
    rng = onp.random.RandomState(0)
    a = np.array(rng.uniform(-1, 1, (11, 13)).astype("float32"))
    b = np.array(rng.uniform(-1, 1, (13, 7)).astype("float32"))
    before = jit_cache_info()
    np.dot(a, b).wait_to_read()               # first call: miss + compile
    np.dot(a, b).wait_to_read()               # second: hit
    after = jit_cache_info()
    assert after["misses"] >= before["misses"]
    assert after["hits"] > before["hits"]
    rep = registry.report()
    now = jit_cache_info()
    # bracket instead of equality: leftover worker threads from earlier
    # suite tests (io prefetch, kvstore servers) may run ops between the
    # two reads
    assert after["hits"] <= rep["mx_jit_cache_hits_total"]["value"] \
        <= now["hits"]
    assert registry.JIT_COMPILE.snapshot()["count"] >= 1


def test_estimator_telemetry_handler(caplog):
    import logging

    class _Est:
        logger = logging.getLogger("telemetry_handler_test")

    h = monitor.TelemetryHandler(interval=0)
    before = registry.EXAMPLES.value
    h.train_begin(_Est)
    h.batch_begin(_Est)
    batch = (np.array(onp.zeros((8, 4), "float32")),
             np.array(onp.zeros((8,), "float32")))
    h.batch_end(_Est, batch=batch)
    assert registry.EXAMPLES.value - before == 8
    with caplog.at_level(logging.INFO, logger="telemetry_handler_test"):
        h.epoch_end(_Est)
    assert any("mx_step_time_seconds" in r.message or
               "mx_step_time_seconds" in str(r.args) for r in caplog.records)


def test_env_knobs_registered():
    from incubator_mxnet_tpu import util

    knobs = util.env_knobs()
    assert "MXNET_TELEMETRY" in knobs
    assert "MXNET_TELEMETRY_INTERVAL" in knobs
    assert not knobs["MXNET_TELEMETRY"][0].startswith("(")   # honored


def test_estimator_batch_processor_raises():
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.contrib.estimator import Estimator

    net = gluon.nn.Dense(2, in_units=4)
    net.initialize()
    with pytest.raises(ValueError, match="batch_processor"):
        Estimator(net, gluon.loss.L2Loss(), batch_processor=object())


def test_framework_lint_fl005_adhoc_timing():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import framework_lint
    finally:
        sys.path.pop(0)
    src = ("import time\n"
           "def kernel(x):\n"
           "    t0 = time.perf_counter()\n"
           "    return x, time.perf_counter() - t0\n")
    findings = framework_lint.lint_source(src, "incubator_mxnet_tpu/ops/k.py")
    assert any(f.rule == "FL005" for f in findings), findings
    # same source OUTSIDE ops/ is fine
    assert not any(f.rule == "FL005" for f in framework_lint.lint_source(
        src, "incubator_mxnet_tpu/gluon/trainer.py"))
    # module-level timing (not in a function body) is fine even in ops/
    top = "import time\nT0 = time.time()\n"
    assert not any(f.rule == "FL005" for f in framework_lint.lint_source(
        top, "incubator_mxnet_tpu/ops/k.py"))
