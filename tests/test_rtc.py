"""mx.rtc (pallas runtime-kernel module) tests (reference model:
tests/python/gpu/test_rtc.py adapted to the TPU pallas path)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _interpret():
    """pallas interpret mode on CPU test platform."""
    import jax

    return jax.devices()[0].platform != "tpu"


def _add_scale_builder(x, scale=1.0):
    import jax
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * scale + 1.0

    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret())(x)


def test_cuda_module_raises_with_guidance():
    with pytest.raises(RuntimeError, match="pallas"):
        mx.rtc.CudaModule("extern C ...")


def test_pallas_module_kernel_executes():
    mod = mx.rtc.PallasModule({"add_scale": _add_scale_builder})
    x = NDArray(onp.arange(8, dtype=onp.float32))
    out = mod.get_kernel("add_scale")(x, scale=2.0)
    onp.testing.assert_allclose(out.asnumpy(),
                                onp.arange(8) * 2.0 + 1.0, rtol=1e-6)


def test_pallas_kernel_launch_signature():
    mod = mx.rtc.PallasModule({"add_scale": _add_scale_builder})
    x = NDArray(onp.ones(4, onp.float32))
    (out,) = mod.get_kernel("add_scale").launch([x], grid_dims=(1, 1, 1),
                                                block_dims=(4, 1, 1))
    onp.testing.assert_allclose(out.asnumpy(), onp.full(4, 2.0))


def test_pallas_kernel_with_custom_vjp_is_differentiable():
    """Gradients require the builder to carry a custom_vjp — the same
    pattern ops/flash_attention.py uses for its backward kernel."""
    import jax

    @jax.custom_vjp
    def scaled(x, scale):
        return _add_scale_builder(x, scale=scale)

    def fwd(x, scale):
        return scaled(x, scale), scale

    def bwd(scale, g):
        return (g * scale, None)

    scaled.defvjp(fwd, bwd)

    mod = mx.rtc.PallasModule(
        {"add_scale": lambda x, scale=1.0: scaled(x, scale)})
    x = NDArray(onp.ones(4, onp.float32))
    x.attach_grad()
    with autograd.record():
        y = mod.get_kernel("add_scale")(x, scale=3.0).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), onp.full(4, 3.0),
                                rtol=1e-5)


def test_unknown_kernel_raises():
    mod = mx.rtc.PallasModule({"add_scale": _add_scale_builder})
    with pytest.raises(ValueError, match="add_scale"):
        mod.get_kernel("nope")
    assert "add_scale" in mod
