"""gluon.probability tests (reference test strategy:
`tests/python/unittest/test_gluon_probability_v2.py` — sampling moments,
log_prob vs scipy, KL numerics vs empirical, autograd through densities)."""
import math

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np
from incubator_mxnet_tpu.gluon import probability as mgp

mx.random.seed(7)


def A(x):
    return onp.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


# ---------------------------------------------------------------------------
# log_prob vs scipy
# ---------------------------------------------------------------------------

def test_normal_log_prob_cdf_icdf():
    from scipy import stats

    loc, scale = 0.7, 1.3
    d = mgp.Normal(loc, scale)
    x = onp.linspace(-3, 3, 11).astype("float32")
    ref = stats.norm(loc, scale)
    onp.testing.assert_allclose(A(d.log_prob(np.array(x))), ref.logpdf(x),
                                rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(A(d.cdf(np.array(x))), ref.cdf(x),
                                rtol=1e-5, atol=1e-5)
    q = onp.linspace(0.05, 0.95, 7).astype("float32")
    onp.testing.assert_allclose(A(d.icdf(np.array(q))), ref.ppf(q),
                                rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("dist,ref_fn,xs", [
    (lambda: mgp.Laplace(0.5, 2.0),
     lambda s: s.laplace(0.5, 2.0), onp.linspace(-4, 4, 9)),
    (lambda: mgp.Cauchy(0.0, 1.5),
     lambda s: s.cauchy(0.0, 1.5), onp.linspace(-4, 4, 9)),
    (lambda: mgp.Exponential(2.0),
     lambda s: s.expon(scale=2.0), onp.linspace(0.1, 5, 9)),
    (lambda: mgp.Gamma(3.0, 0.5),
     lambda s: s.gamma(3.0, scale=0.5), onp.linspace(0.1, 5, 9)),
    (lambda: mgp.Beta(2.0, 3.0),
     lambda s: s.beta(2.0, 3.0), onp.linspace(0.05, 0.95, 9)),
    (lambda: mgp.Gumbel(0.5, 1.2),
     lambda s: s.gumbel_r(0.5, 1.2), onp.linspace(-3, 5, 9)),
    (lambda: mgp.Weibull(1.7, 2.0),
     lambda s: s.weibull_min(1.7, scale=2.0), onp.linspace(0.1, 5, 9)),
    (lambda: mgp.StudentT(4.0, 0.0, 1.0),
     lambda s: s.t(4.0), onp.linspace(-4, 4, 9)),
    (lambda: mgp.Pareto(3.0, 1.0),
     lambda s: s.pareto(3.0), onp.linspace(1.1, 5, 9)),
    (lambda: mgp.HalfNormal(1.5),
     lambda s: s.halfnorm(scale=1.5), onp.linspace(0.1, 4, 9)),
    (lambda: mgp.HalfCauchy(1.0),
     lambda s: s.halfcauchy(scale=1.0), onp.linspace(0.1, 4, 9)),
    (lambda: mgp.Chi2(5.0),
     lambda s: s.chi2(5.0), onp.linspace(0.5, 10, 9)),
    (lambda: mgp.FisherSnedecor(5.0, 7.0),
     lambda s: s.f(5.0, 7.0), onp.linspace(0.2, 4, 9)),
    (lambda: mgp.Poisson(3.0),
     lambda s: s.poisson(3.0), onp.arange(0, 9)),
    (lambda: mgp.Geometric(prob=0.3),
     lambda s: s.geom(0.3, loc=-1), onp.arange(0, 9)),
    (lambda: mgp.Binomial(10, prob=0.4),
     lambda s: s.binom(10, 0.4), onp.arange(0, 11)),
])
def test_log_prob_vs_scipy(dist, ref_fn, xs):
    from scipy import stats

    d = dist()
    ref = ref_fn(stats)
    xs = xs.astype("float32")
    got = A(d.log_prob(np.array(xs)))
    want = (ref.logpmf(xs) if hasattr(ref, "logpmf") else ref.logpdf(xs))
    onp.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_uniform_log_prob_support():
    d = mgp.Uniform(1.0, 3.0)
    lp = A(d.log_prob(np.array([0.5, 2.0, 3.5], dtype="float32")))
    assert lp[0] == -onp.inf and lp[2] == -onp.inf
    onp.testing.assert_allclose(lp[1], -math.log(2.0), rtol=1e-6)


def test_categorical_and_onehot():
    from scipy import stats  # noqa: F401

    logits = onp.log(onp.array([0.2, 0.3, 0.5], dtype="float32"))
    c = mgp.Categorical(3, logit=np.array(logits))
    lp = A(c.log_prob(np.array([0.0, 1.0, 2.0])))
    onp.testing.assert_allclose(onp.exp(lp), [0.2, 0.3, 0.5], rtol=1e-5)
    s = c.sample((1000,))
    assert set(onp.unique(A(s))).issubset({0.0, 1.0, 2.0})
    sup = A(c.enumerate_support())
    onp.testing.assert_allclose(sup, [0.0, 1.0, 2.0])

    oh = mgp.OneHotCategorical(3, prob=np.array([0.2, 0.3, 0.5],
                                                dtype="float32"))
    v = onp.eye(3, dtype="float32")
    onp.testing.assert_allclose(onp.exp(A(oh.log_prob(np.array(v)))),
                                [0.2, 0.3, 0.5], rtol=1e-5)
    assert A(oh.sample((50,))).shape == (50, 3)


def test_mvn_log_prob_and_sample():
    from scipy import stats

    loc = onp.array([0.5, -0.3], dtype="float32")
    cov = onp.array([[1.2, 0.4], [0.4, 0.9]], dtype="float32")
    d = mgp.MultivariateNormal(np.array(loc), cov=np.array(cov))
    x = onp.array([[0.0, 0.0], [1.0, -1.0]], dtype="float32")
    ref = stats.multivariate_normal(loc, cov)
    onp.testing.assert_allclose(A(d.log_prob(np.array(x))), ref.logpdf(x),
                                rtol=1e-4)
    s = A(d.sample((4000,)))
    onp.testing.assert_allclose(s.mean(0), loc, atol=0.1)
    onp.testing.assert_allclose(onp.cov(s.T), cov, atol=0.15)
    # scale_tril / precision parameterizations agree
    lt = onp.linalg.cholesky(cov).astype("float32")
    d2 = mgp.MultivariateNormal(np.array(loc), scale_tril=np.array(lt))
    d3 = mgp.MultivariateNormal(np.array(loc),
                                precision=np.array(onp.linalg.inv(cov)))
    onp.testing.assert_allclose(A(d2.log_prob(np.array(x))), ref.logpdf(x),
                                rtol=1e-4)
    onp.testing.assert_allclose(A(d3.log_prob(np.array(x))), ref.logpdf(x),
                                rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# sampling moments + shapes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dist,mean,var", [
    (lambda: mgp.Normal(1.0, 2.0), 1.0, 4.0),
    (lambda: mgp.Laplace(0.0, 1.0), 0.0, 2.0),
    (lambda: mgp.Exponential(2.0), 2.0, 4.0),
    (lambda: mgp.Gamma(2.0, 1.5), 3.0, 4.5),
    (lambda: mgp.Beta(2.0, 2.0), 0.5, 0.05),
    (lambda: mgp.Poisson(4.0), 4.0, 4.0),
    (lambda: mgp.Bernoulli(prob=0.3), 0.3, 0.21),
    (lambda: mgp.Uniform(0.0, 2.0), 1.0, 1.0 / 3),
    (lambda: mgp.Gumbel(0.0, 1.0), onp.euler_gamma, math.pi ** 2 / 6),
])
def test_sample_moments(dist, mean, var):
    d = dist()
    s = A(d.sample((6000,)))
    assert abs(s.mean() - mean) < 6 * math.sqrt(var / 6000) + 0.02
    assert abs(s.var() - var) < 0.25 * max(var, 0.15)
    onp.testing.assert_allclose(A(d.mean), mean, rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(A(d.variance), var, rtol=1e-4, atol=1e-5)


def test_sample_shapes_and_sample_n():
    d = mgp.Normal(np.zeros((3, 2)), np.ones((3, 2)))
    assert d.sample().shape == (3, 2)
    assert d.sample((5, 3, 2)).shape == (5, 3, 2)
    assert d.sample_n(7).shape == (7, 3, 2)
    dd = mgp.Dirichlet(np.ones((4, 3)))
    assert dd.sample().shape == (4, 3)
    s = A(dd.sample())
    onp.testing.assert_allclose(s.sum(-1), onp.ones(4), rtol=1e-5)
    b = d.broadcast_to((5, 3, 2))
    assert b.sample().shape == (5, 3, 2)


# ---------------------------------------------------------------------------
# entropy / KL
# ---------------------------------------------------------------------------

def test_entropy_closed_forms():
    from scipy import stats

    pairs = [
        (mgp.Normal(0.5, 1.5), stats.norm(0.5, 1.5)),
        (mgp.Laplace(0.0, 2.0), stats.laplace(0.0, 2.0)),
        (mgp.Exponential(0.7), stats.expon(scale=0.7)),
        (mgp.Gamma(2.5, 1.2), stats.gamma(2.5, scale=1.2)),
        (mgp.Beta(2.0, 3.0), stats.beta(2.0, 3.0)),
        (mgp.Gumbel(0.0, 1.3), stats.gumbel_r(0.0, 1.3)),
        (mgp.Uniform(1.0, 4.0), stats.uniform(1.0, 3.0)),
    ]
    for d, ref in pairs:
        onp.testing.assert_allclose(A(d.entropy()), ref.entropy(),
                                    rtol=1e-4, atol=1e-4)


def test_bernoulli_exponential_family_entropy():
    # generic ExponentialFamily.entropy (Bregman identity) matches closed form
    p = 0.3
    d = mgp.Bernoulli(prob=p)
    want = -(p * math.log(p) + (1 - p) * math.log(1 - p))
    onp.testing.assert_allclose(A(mgp.ExponentialFamily.entropy(d)), want,
                                rtol=1e-4)
    onp.testing.assert_allclose(A(d.entropy()), want, rtol=1e-4)


@pytest.mark.parametrize("p,q", [
    (lambda: mgp.Normal(0.0, 1.0), lambda: mgp.Normal(1.0, 2.0)),
    (lambda: mgp.Gamma(2.0, 1.0), lambda: mgp.Gamma(3.0, 0.5)),
    (lambda: mgp.Beta(2.0, 2.0), lambda: mgp.Beta(3.0, 1.5)),
    (lambda: mgp.Laplace(0.0, 1.0), lambda: mgp.Laplace(0.5, 2.0)),
    (lambda: mgp.Poisson(3.0), lambda: mgp.Poisson(5.0)),
    (lambda: mgp.Bernoulli(prob=0.3), lambda: mgp.Bernoulli(prob=0.6)),
    (lambda: mgp.Exponential(1.0), lambda: mgp.Exponential(2.0)),
    (lambda: mgp.Geometric(prob=0.4), lambda: mgp.Geometric(prob=0.2)),
    (lambda: mgp.Categorical(3, prob=np.array([0.2, 0.3, 0.5])),
     lambda: mgp.Categorical(3, prob=np.array([0.5, 0.25, 0.25]))),
])
def test_kl_vs_empirical(p, q):
    mx.random.seed(11)
    P, Q = p(), q()
    kl = A(mgp.kl_divergence(P, Q))
    est = A(mgp.empirical_kl(P, Q, n_samples=40000))
    assert abs(kl - est) < max(0.08, 0.15 * abs(kl)), (kl, est)


def test_kl_mvn():
    loc = onp.array([0.0, 0.0], dtype="float32")
    c1 = onp.array([[1.0, 0.2], [0.2, 1.0]], dtype="float32")
    c2 = onp.array([[2.0, -0.3], [-0.3, 1.5]], dtype="float32")
    P = mgp.MultivariateNormal(np.array(loc), cov=np.array(c1))
    Q = mgp.MultivariateNormal(np.array(loc) + 0.5, cov=np.array(c2))
    kl = A(mgp.kl_divergence(P, Q))
    # closed form cross-check in numpy
    ic2 = onp.linalg.inv(c2)
    diff = onp.array([0.5, 0.5])
    want = 0.5 * (onp.log(onp.linalg.det(c2) / onp.linalg.det(c1)) - 2
                  + onp.trace(ic2 @ c1) + diff @ ic2 @ diff)
    onp.testing.assert_allclose(kl, want, rtol=1e-4)


def test_kl_independent_and_chi2_dispatch():
    P = mgp.Independent(mgp.Normal(np.zeros(4), np.ones(4)), 1)
    Q = mgp.Independent(mgp.Normal(np.ones(4), np.ones(4)), 1)
    onp.testing.assert_allclose(A(mgp.kl_divergence(P, Q)), 2.0, rtol=1e-5)
    # Chi2 → Gamma formula via MRO dispatch
    kl = A(mgp.kl_divergence(mgp.Chi2(4.0), mgp.Gamma(2.0, 2.0)))
    assert abs(kl) < 1e-5  # Chi2(4) IS Gamma(2, 2)


# ---------------------------------------------------------------------------
# gradients
# ---------------------------------------------------------------------------

def test_pathwise_gradient_normal():
    loc = np.array([0.5])
    scale = np.array([1.0])
    loc.attach_grad()
    scale.attach_grad()
    mx.random.seed(3)
    with autograd.record():
        d = mgp.Normal(loc, scale)
        s = d.sample((256,))
        loss = np.sum(s * s) / 256  # E[x^2] = loc^2 + scale^2
    loss.backward()
    # d/dloc E[x^2] = 2*loc;  d/dscale = 2*scale
    assert abs(float(A(loc.grad)) - 2 * 0.5) < 0.4
    assert abs(float(A(scale.grad)) - 2 * 1.0) < 0.5


def test_log_prob_gradient():
    loc = np.array(0.0)
    loc.attach_grad()
    with autograd.record():
        lp = mgp.Normal(loc, 1.0).log_prob(np.array(1.5))
    lp.backward()
    onp.testing.assert_allclose(A(loc.grad), 1.5, rtol=1e-5)


def test_gamma_implicit_reparam_grad():
    a = np.array(2.0)
    a.attach_grad()
    mx.random.seed(5)
    with autograd.record():
        s = mgp.Gamma(a, 1.0).sample((512,))
        m = np.mean(s)
    m.backward()
    # dE[x]/da = scale = 1
    assert abs(float(A(a.grad)) - 1.0) < 0.35


def test_relaxed_bernoulli_pathwise():
    logit = np.array(0.3)
    logit.attach_grad()
    with autograd.record():
        d = mgp.RelaxedBernoulli(0.5, logit=logit)
        s = d.sample((128,))
        m = np.mean(s)
    m.backward()
    assert float(A(logit.grad)) > 0  # increasing logit increases samples


# ---------------------------------------------------------------------------
# transformations
# ---------------------------------------------------------------------------

def test_transformed_distribution_lognormal():
    from scipy import stats

    base = mgp.Normal(0.2, 0.5)
    d = mgp.TransformedDistribution(base, mgp.ExpTransform())
    ref = stats.lognorm(0.5, scale=math.exp(0.2))
    x = onp.linspace(0.3, 3, 9).astype("float32")
    onp.testing.assert_allclose(A(d.log_prob(np.array(x))), ref.logpdf(x),
                                rtol=1e-4)
    onp.testing.assert_allclose(A(d.cdf(np.array(x))), ref.cdf(x), rtol=1e-4)
    s = A(d.sample((2000,)))
    assert (s > 0).all()


def test_compose_and_inverse_transform():
    t = mgp.ComposeTransform([mgp.ExpTransform(),
                              mgp.AffineTransform(1.0, 2.0)])
    x = np.array([0.0, 0.5], dtype="float32")
    y = t(x)
    onp.testing.assert_allclose(A(y), 1 + 2 * onp.exp(A(x)), rtol=1e-5)
    x_back = t.inv(y)
    onp.testing.assert_allclose(A(x_back), A(x), rtol=1e-5, atol=1e-6)
    ldj = A(t.log_det_jacobian(x, y))
    onp.testing.assert_allclose(ldj, A(x) + math.log(2.0), rtol=1e-5)


def test_biject_to_domains():
    from incubator_mxnet_tpu.gluon.probability import biject_to
    from incubator_mxnet_tpu.gluon.probability.distributions import constraint

    x = np.array([-2.0, 0.0, 2.0], dtype="float32")
    pos = biject_to(constraint.Positive())(x)
    assert (A(pos) > 0).all()
    unit = biject_to(constraint.UnitInterval())(x)
    assert ((A(unit) > 0) & (A(unit) < 1)).all()
    gt = biject_to(constraint.GreaterThan(3.0))(x)
    assert (A(gt) > 3).all()
    simplex = biject_to(constraint.Simplex())(np.array([[0.1, 0.2, 0.3]]))
    onp.testing.assert_allclose(A(simplex).sum(-1), 1.0, rtol=1e-5)


def test_validate_args():
    with pytest.raises(ValueError):
        mgp.Normal(0.0, -1.0, validate_args=True)
    d = mgp.Bernoulli(prob=0.5, validate_args=True)
    with pytest.raises(ValueError):
        d.log_prob(np.array([0.5]))  # not in {0,1}


# ---------------------------------------------------------------------------
# StochasticBlock
# ---------------------------------------------------------------------------

def test_stochastic_block_vae_style():
    from incubator_mxnet_tpu.gluon import nn

    class Sampler(mgp.StochasticBlock):
        def __init__(self):
            super().__init__()
            self.dense = nn.Dense(4)

        @mgp.StochasticBlock.collectLoss
        def forward(self, x):
            h = self.dense(x)
            loc, logs = h[:, :2], h[:, 2:]
            scale = np.exp(logs)
            qz = mgp.Normal(loc, scale)
            pz = mgp.Normal(np.zeros_like(loc), np.ones_like(scale))
            self.add_loss(mgp.kl_divergence(qz, pz))
            return qz.sample()

    net = Sampler()
    net.initialize()
    x = np.ones((3, 5))
    out = net(x)
    assert out.shape == (3, 2)
    assert len(net.losses) == 1
    assert net.losses[0].shape == (3, 2)

    # losses participate in autograd
    with autograd.record():
        out = net(x)
        loss = np.sum(out * 0) + np.sum(net.losses[0])
    loss.backward()
    g = net.dense.weight.grad()
    assert float(np.sum(np.abs(g)).asnumpy() if hasattr(g, "asnumpy")
                 else onp.abs(A(g)).sum()) > 0


def test_stochastic_block_requires_decorator():
    class Bad(mgp.StochasticBlock):
        def forward(self, x):
            return x

    net = Bad()
    net.initialize()
    with pytest.raises(ValueError):
        net(np.ones((2, 2)))
