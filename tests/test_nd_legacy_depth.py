"""Legacy `mx.nd` namespace depth: CamelCase op aliases, broadcast_*
family, NDArray methods and conversions (reference:
`tests/python/unittest/test_ndarray.py` / `test_operator.py` legacy
surface)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd, np

RNG = onp.random.RandomState(59)


def _a(*shape):
    return nd.array(RNG.uniform(-2, 2, shape).astype("float32"))


# -- CamelCase aliases -------------------------------------------------------

def test_fullyconnected_alias():
    x, w, b = _a(2, 5), _a(3, 5), _a(3)
    out = nd.FullyConnected(x, w, b, num_hidden=3)
    onp.testing.assert_allclose(
        out.asnumpy(), x.asnumpy() @ w.asnumpy().T + b.asnumpy(),
        rtol=1e-5)


def test_activation_alias():
    x = _a(3, 3)
    out = nd.Activation(x, act_type="relu").asnumpy()
    onp.testing.assert_allclose(out, onp.maximum(x.asnumpy(), 0),
                                rtol=1e-6)


def test_convolution_alias():
    x, w = _a(1, 2, 6, 6), _a(3, 2, 3, 3)
    out = nd.Convolution(x, w, None, kernel=(3, 3), num_filter=3,
                         no_bias=True)
    assert out.shape == (1, 3, 4, 4)


def test_pooling_alias():
    x = _a(1, 2, 4, 4)
    out = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert out.shape == (1, 2, 2, 2)


def test_flatten_alias():
    assert nd.Flatten(_a(2, 3, 4)).shape == (2, 12)


def test_concat_alias():
    a, b = _a(2, 3), _a(2, 3)
    out = nd.Concat(a, b, dim=1)
    assert out.shape == (2, 6)


def test_concat_default_dim_is_1():
    # reference ConcatParam: dim defaults to 1 (concat-inl.h set_default(1))
    a, b = _a(2, 3), _a(2, 3)
    assert nd.concat(a, b).shape == (2, 6)
    assert nd.Concat(a, b).shape == (2, 6)

    from incubator_mxnet_tpu import symbol as sym

    va = sym.Variable("a")
    vb = sym.Variable("b")
    out = sym.Concat(va, vb)
    ex = out.bind(args={"a": a, "b": b})
    assert ex.forward()[0].shape == (2, 6)


def test_reshape_alias():
    assert nd.Reshape(_a(4, 3), shape=(3, 4)).shape == (3, 4)


def test_swapaxis_alias():
    assert nd.SwapAxis(_a(2, 3, 4), dim1=0, dim2=2).shape == (4, 3, 2)


def test_cast_alias():
    out = nd.Cast(_a(2, 2), dtype="float16")
    assert "float16" in str(out.dtype)


def test_embedding_alias():
    w = _a(5, 3)
    idx = nd.array(onp.array([0, 4], "float32"))
    out = nd.Embedding(idx, w, input_dim=5, output_dim=3)
    onp.testing.assert_array_equal(out.asnumpy(),
                                   w.asnumpy()[[0, 4]])


def test_batchnorm_alias_inference():
    x = _a(2, 3, 4, 4)
    out = nd.BatchNorm(x, nd.ones((3,)), nd.zeros((3,)),
                       nd.zeros((3,)), nd.ones((3,)))
    assert out.shape == x.shape


# -- broadcast_* family ------------------------------------------------------

def test_broadcast_add():
    a, b = _a(3, 4), _a(1, 4)
    onp.testing.assert_allclose(nd.broadcast_add(a, b).asnumpy(),
                                a.asnumpy() + b.asnumpy(), rtol=1e-6)


def test_broadcast_mul_div():
    a, b = _a(3, 1), _a(1, 4)
    onp.testing.assert_allclose(nd.broadcast_mul(a, b).asnumpy(),
                                a.asnumpy() * b.asnumpy(), rtol=1e-6)
    c = nd.array(onp.abs(b.asnumpy()) + 0.5)
    onp.testing.assert_allclose(nd.broadcast_div(a, c).asnumpy(),
                                a.asnumpy() / c.asnumpy(), rtol=1e-5)


def test_broadcast_maximum_minimum():
    a, b = _a(3, 4), _a(3, 4)
    onp.testing.assert_allclose(nd.broadcast_maximum(a, b).asnumpy(),
                                onp.maximum(a.asnumpy(), b.asnumpy()))
    onp.testing.assert_allclose(nd.broadcast_minimum(a, b).asnumpy(),
                                onp.minimum(a.asnumpy(), b.asnumpy()))


def test_elemwise_family():
    a, b = _a(3, 3), _a(3, 3)
    onp.testing.assert_allclose(nd.elemwise_add(a, b).asnumpy(),
                                a.asnumpy() + b.asnumpy(), rtol=1e-6)
    onp.testing.assert_allclose(nd.elemwise_mul(a, b).asnumpy(),
                                a.asnumpy() * b.asnumpy(), rtol=1e-6)


def test_add_n_sums_all():
    a, b, c = _a(2, 2), _a(2, 2), _a(2, 2)
    onp.testing.assert_allclose(
        nd.add_n(a, b, c).asnumpy(),
        a.asnumpy() + b.asnumpy() + c.asnumpy(), rtol=1e-6)


def test_elementwisesum_alias():
    a, b = _a(2, 2), _a(2, 2)
    onp.testing.assert_allclose(nd.ElementWiseSum(a, b).asnumpy(),
                                a.asnumpy() + b.asnumpy(), rtol=1e-6)


# -- creation + conversion ---------------------------------------------------

def test_nd_zeros_ones_full():
    assert nd.zeros((2, 3)).asnumpy().sum() == 0
    assert nd.ones((2, 3)).asnumpy().sum() == 6
    onp.testing.assert_array_equal(nd.full((2, 2), 7.0).asnumpy(),
                                   onp.full((2, 2), 7.0))


def test_nd_array_from_list():
    out = nd.array([[1, 2], [3, 4]])
    assert out.shape == (2, 2)


def test_asnumpy_roundtrip():
    a = RNG.uniform(-1, 1, (3, 3)).astype("float32")
    onp.testing.assert_array_equal(nd.array(a).asnumpy(), a)


def test_asscalar():
    assert nd.array(onp.array([3.5], "float32")).asscalar() == \
        pytest.approx(3.5)


def test_astype_copy():
    a = _a(2, 2)
    b = a.astype("float64" if False else "float16")
    assert "float16" in str(b.dtype)


def test_copyto():
    a = _a(2, 2)
    b = nd.zeros((2, 2))
    a.copyto(b)
    onp.testing.assert_array_equal(b.asnumpy(), a.asnumpy())


def test_wait_to_read_and_waitall():
    a = _a(8, 8)
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert b.shape == (8, 8)


def test_context_attribute():
    a = _a(2)
    assert a.context is not None
    assert a.ctx is not None


# -- methods -----------------------------------------------------------------

def test_method_reductions():
    a = _a(3, 4)
    av = a.asnumpy()
    assert a.sum().asscalar() == pytest.approx(av.sum(), rel=1e-5)
    assert a.max().asscalar() == pytest.approx(av.max(), rel=1e-5)
    assert a.min().asscalar() == pytest.approx(av.min(), rel=1e-5)
    assert a.mean().asscalar() == pytest.approx(av.mean(), rel=1e-5)


def test_method_elementwise():
    a = _a(3, 3)
    onp.testing.assert_allclose(a.abs().asnumpy(), onp.abs(a.asnumpy()),
                                rtol=1e-6)
    onp.testing.assert_allclose(a.square().asnumpy(),
                                a.asnumpy() ** 2, rtol=1e-6)


def test_method_clip_round():
    a = _a(3, 3)
    onp.testing.assert_allclose(a.clip(-1, 1).asnumpy(),
                                onp.clip(a.asnumpy(), -1, 1), rtol=1e-6)


def test_method_expand_dims_squeeze():
    a = _a(3, 4)
    e = a.expand_dims(0)
    assert e.shape == (1, 3, 4)
    assert e.squeeze().shape == (3, 4)


def test_method_slice_ops():
    a = _a(6, 4)
    onp.testing.assert_array_equal(a.slice_axis(axis=0, begin=1,
                                                end=4).asnumpy(),
                                   a.asnumpy()[1:4])
    # legacy nd.take: axis defaults to 0 (row gather), unlike numpy's
    # flattening .take method default
    onp.testing.assert_array_equal(nd.take(a, nd.array(
        onp.array([0, 5], "float32"))).asnumpy(),
        a.asnumpy()[[0, 5]])


def test_tile_repeat_methods():
    a = _a(2, 2)
    assert a.tile((2, 2)).shape == (4, 4)
    assert a.repeat(2, axis=0).shape == (4, 2)


def test_sequence_ops_via_nd():
    x = _a(4, 2)          # (T, N)
    vl = nd.array(onp.array([2, 3], "float32"))
    out = nd.SequenceMask(x, vl, use_sequence_length=True).asnumpy()
    assert out[2, 0] == 0 and out[3, 1] == 0


def test_one_hot_alias():
    out = nd.one_hot(nd.array(onp.array([1, 0], "float32")), 3)
    onp.testing.assert_array_equal(
        out.asnumpy(), [[0, 1, 0], [1, 0, 0]])


def test_topk_pick():
    a = nd.array(onp.array([[1.0, 5.0, 3.0]], "float32"))
    v = nd.topk(a, k=1, ret_typ="value", axis=-1)
    assert float(v.asnumpy()[0, 0]) == 5.0
    p = nd.pick(a, nd.array(onp.array([2.0], "float32")))
    assert float(p.asnumpy()[0]) == 3.0


def test_norm_l2():
    a = _a(3, 3)
    got = float(nd.norm(a).asscalar())
    assert got == pytest.approx(float(onp.linalg.norm(a.asnumpy())),
                                rel=1e-5)


def test_dot_matches():
    a, b = _a(3, 4), _a(4, 5)
    onp.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                                a.asnumpy() @ b.asnumpy(), rtol=1e-4)


def test_stack_concat_free_functions():
    a, b = _a(2, 3), _a(2, 3)
    assert nd.stack(a, b).shape == (2, 2, 3)
    assert nd.concat(a, b, dim=0).shape == (4, 3)


def test_save_load_roundtrip(tmp_path):
    a, b = _a(2, 3), _a(4)
    p = str(tmp_path / "arrays.nd")
    nd.save(p, {"a": a, "b": b})
    loaded = nd.load(p)
    onp.testing.assert_array_equal(loaded["a"].asnumpy(), a.asnumpy())
    onp.testing.assert_array_equal(loaded["b"].asnumpy(), b.asnumpy())


def test_save_load_list(tmp_path):
    a = _a(3)
    p = str(tmp_path / "list.nd")
    nd.save(p, [a])
    loaded = nd.load(p)
    onp.testing.assert_array_equal(loaded[0].asnumpy(), a.asnumpy())