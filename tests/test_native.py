"""Native runtime tests: librtio RecordIO reader + the custom-op extension
ABI (reference: C++ IO layer + `tests/python/unittest/test_extensions.py`
MXLoadLib cases). Skipped when no C++ toolchain is present."""
import os
import shutil
import subprocess

import numpy as onp
import pytest

from incubator_mxnet_tpu import np
from incubator_mxnet_tpu import _native
from incubator_mxnet_tpu.recordio import (IndexCreator, IRHeader,
                                          MXIndexedRecordIO, MXRecordIO,
                                          pack, unpack)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def native_libs():
    subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                   check=True, capture_output=True)
    return os.path.join(REPO, "build")


def _write_rec(tmp_path, n=20):
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = []
    for i in range(n):
        payload = pack(IRHeader(0, float(i), i, 0),
                       bytes([i % 251]) * (10 + 13 * i))
        rec.write_idx(i, payload)
        payloads.append(payload)
    rec.close()
    return rec_path, idx_path, payloads


def test_rtio_reader_matches_python(tmp_path, native_libs):
    rec_path, idx_path, payloads = _write_rec(tmp_path)
    f = _native.NativeRecordFile(rec_path)
    assert len(f) == len(payloads)
    for i, want in enumerate(payloads):
        assert f.read(i) == want
    f.close()


def test_rtio_batch_read(tmp_path, native_libs):
    rec_path, idx_path, payloads = _write_rec(tmp_path)
    f = _native.NativeRecordFile(rec_path)
    idxs = [3, 0, 17, 17, 5]
    got = f.read_batch(idxs)
    assert got == [payloads[i] for i in idxs]
    f.close()


def test_rtio_build_index_matches_python(tmp_path, native_libs):
    rec_path, idx_path, _ = _write_rec(tmp_path)
    native_idx = str(tmp_path / "native.idx")
    n = _native.build_index(rec_path, native_idx)
    assert n == 20
    assert open(native_idx).read() == open(idx_path).read()


def test_indexed_recordio_read_batch(tmp_path, native_libs):
    rec_path, idx_path, payloads = _write_rec(tmp_path)
    rec = MXIndexedRecordIO(idx_path, rec_path, "r")
    got = rec.read_batch([2, 9, 2])
    assert got == [payloads[2], payloads[9], payloads[2]]
    # payloads still unpack correctly
    header, content = unpack(got[1])
    assert header.label == 9.0
    rec.close()


def test_index_creator_uses_native(tmp_path, native_libs):
    rec_path, idx_path, _ = _write_rec(tmp_path)
    out_idx = str(tmp_path / "rebuilt.idx")
    c = IndexCreator(rec_path, out_idx)
    c.create_index()
    c.close()
    assert open(out_idx).read() == open(idx_path).read()


# -- extension ABI ------------------------------------------------------------

def test_extension_load_and_run(native_libs):
    from incubator_mxnet_tpu import library, npx

    ops = library.load(os.path.join(native_libs, "libexample_ext.so"),
                       verbose=False)
    assert set(ops) == {"my_relu", "my_gelu"}
    x = np.array(onp.array([-1.0, 0.5, 2.0], "float32"))
    out = npx.my_relu(x)
    onp.testing.assert_array_equal(out.asnumpy(), [0.0, 0.5, 2.0])
    gelu = npx.my_gelu(x).asnumpy()
    import math

    want = [0.5 * v * (1 + math.tanh(0.7978845608 * (v + 0.044715 * v ** 3)))
            for v in [-1.0, 0.5, 2.0]]
    onp.testing.assert_allclose(gelu, want, rtol=1e-5)


def test_extension_op_under_hybridize(native_libs):
    """pure_callback bridging: the C op must run inside a jit-compiled
    (hybridized) forward."""
    from incubator_mxnet_tpu import gluon, library
    from incubator_mxnet_tpu.gluon.block import HybridBlock

    ops = library.load(os.path.join(native_libs, "libexample_ext.so"),
                       verbose=False)
    my_relu = ops["my_relu"]

    class Net(HybridBlock):
        def __init__(self):
            super().__init__()
            self.dense = gluon.nn.Dense(4)

        def forward(self, x):
            return my_relu(self.dense(x))

    net = Net()
    net.initialize()
    net.hybridize()
    x = np.random.uniform(low=-1, size=(2, 3))
    y_eager = net(x)          # eager (completes deferred init)
    y_jit = net(x)            # compiled replay through pure_callback
    onp.testing.assert_allclose(y_eager.asnumpy(), y_jit.asnumpy(),
                                rtol=1e-6)
    assert (y_jit.asnumpy() >= 0).all()


def test_extension_bad_library_rejected(tmp_path, native_libs):
    from incubator_mxnet_tpu import library

    with pytest.raises((ValueError, OSError)):
        library.load(os.path.join(native_libs, "librtio.so"))


def test_extension_partitioner_rewrites_net(native_libs):
    """ABI v2: an out-of-tree .so registers a partitioner ('fc_fuser')
    and a graph pass ('norm_fuser'); optimize_for(backend='fc_fuser')
    must apply its directives — fusing dense+activation chains into
    named segments — without changing the math."""
    from incubator_mxnet_tpu import gluon, library, partition

    library.load(os.path.join(native_libs, "libexample_partitioner.so"),
                 verbose=False)
    assert "fc_fuser" in partition.list_backends()
    assert "norm_fuser" in partition.list_backends()

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(8, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize()
    x = np.random.uniform(low=-1, size=(2, 12))
    y_ref = net(x).asnumpy()

    y_opt = net.optimize_for(x, backend="fc_fuser").asnumpy()
    backend = partition.get_backend("fc_fuser")
    assert backend.last_rewrites >= 2      # both dense+relu chains fused
    onp.testing.assert_allclose(y_opt, y_ref, rtol=1e-5, atol=1e-6)


def test_extension_pass_fuses_layernorm(native_libs):
    from incubator_mxnet_tpu import gluon, library, partition

    library.load(os.path.join(native_libs, "libexample_partitioner.so"),
                 verbose=False)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.LayerNorm())
    net.initialize()
    x = np.random.uniform(low=-1, size=(2, 6))
    y_ref = net(x).asnumpy()
    y_opt = net.optimize_for(x, backend="norm_fuser").asnumpy()
    assert partition.get_backend("norm_fuser").last_rewrites >= 1
    onp.testing.assert_allclose(y_opt, y_ref, rtol=1e-5, atol=1e-6)


def test_extension_abi_handshake(native_libs, tmp_path):
    """A library reporting a FUTURE ABI version must be rejected."""
    import subprocess as sp

    src = tmp_path / "future_ext.cc"
    src.write_text("""
#include <cstdint>
extern "C" {
int mx_ext_abi_version(void) { return 99; }
int mx_ext_num_ops(void) { return 0; }
const char* mx_ext_op_name(int) { return nullptr; }
int mx_ext_op_infer_shape(int, int, const int64_t* const*, const int*,
                          int64_t*, int*) { return -1; }
int mx_ext_op_forward(int, int, const void*, void*) { return -1; }
}
""")
    so = tmp_path / "libfuture_ext.so"
    sp.run(["g++", "-shared", "-fPIC", "-o", str(so), str(src)],
           check=True, capture_output=True)
    from incubator_mxnet_tpu import library

    with pytest.raises(ValueError, match="ABI 99 unsupported"):
        library.load(str(so), verbose=False)
