"""KVStore row_sparse push/pull (reference: `src/kvstore/kvstore_local.h:232`
PushImpl row_sparse merge, `:279` PullRowSparseImpl) and the Trainer wiring
for `Embedding(sparse_grad=True)` — the BERT-scale embedding path."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, kvstore, np
from incubator_mxnet_tpu.ndarray import sparse


def A(x):
    return x.asnumpy()


def _rs(rows, vals, shape):
    return sparse.row_sparse_array(
        (onp.asarray(vals, "float32"), onp.asarray(rows, "int64")),
        shape=shape)


def test_push_merges_row_sparse_copies():
    """Per-device sparse gradient copies merge by gather-unique-sum and the
    store entry STAYS row_sparse."""
    kv = kvstore.create("device")
    g1 = _rs([1, 3], [[1.0, 1.0], [2.0, 2.0]], (6, 2))
    g2 = _rs([3, 4], [[10.0, 10.0], [4.0, 4.0]], (6, 2))
    kv.push("emb", [g1, g2])
    got = kv.pull("emb")
    assert got.stype == "row_sparse"
    dense = onp.zeros((6, 2), "float32")
    dense[1] = 1.0
    dense[3] = 12.0
    dense[4] = 4.0
    onp.testing.assert_allclose(A(got), dense, rtol=1e-6)
    # merged storage is canonical: unique sorted rows only
    onp.testing.assert_array_equal(A(got.indices), [1, 3, 4])


def test_push_rejects_mixed_stypes():
    import pytest

    kv = kvstore.create("local")
    g1 = _rs([0], [[1.0, 1.0]], (4, 2))
    g2 = np.zeros((4, 2))
    with pytest.raises(ValueError):
        kv.push("k", [g1, g2])


def test_row_sparse_pull_slices_rows():
    kv = kvstore.create("local")
    w = onp.random.RandomState(0).uniform(-1, 1, (8, 3)).astype("float32")
    kv.init("emb", np.array(w))
    out = kv.row_sparse_pull("emb", row_ids=np.array(
        onp.array([5, 2, 5], "float32")))
    assert out.stype == "row_sparse"
    onp.testing.assert_array_equal(A(out.indices), [2, 5])
    onp.testing.assert_allclose(A(out.data), w[[2, 5]], rtol=1e-6)
    # out= write form
    dst = sparse.zeros("row_sparse", (8, 3))
    kv.row_sparse_pull("emb", out=dst, row_ids=np.array(
        onp.array([0, 7], "float32")))
    onp.testing.assert_allclose(A(dst.data), w[[0, 7]], rtol=1e-6)


def test_pushpull_keeps_grad_sparse():
    kv = kvstore.create("device")
    g = _rs([2, 2, 5], [[1.0], [3.0], [7.0]], (6, 1))
    kv.pushpull(0, g, out=g)
    assert g.stype == "row_sparse"
    onp.testing.assert_array_equal(A(g.indices), [2, 5])
    onp.testing.assert_allclose(A(g.data), [[4.0], [7.0]], rtol=1e-6)


def test_updater_receives_sparse_and_updates_lazily():
    """push with a kvstore-side optimizer: only touched rows move
    (reference: server-side ApplyUpdates with row_sparse,
    `kvstore_dist_server.h:349`)."""
    from incubator_mxnet_tpu import optimizer

    kv = kvstore.create("local")
    w = onp.ones((5, 2), "float32")
    kv.init("emb", np.array(w))
    kv.set_optimizer(optimizer.SGD(learning_rate=1.0))
    kv.push("emb", _rs([1, 4], [[1.0, 1.0], [2.0, 2.0]], (5, 2)))
    got = A(kv.pull("emb"))
    onp.testing.assert_allclose(got[0], [1.0, 1.0])
    onp.testing.assert_allclose(got[1], [0.0, 0.0])
    onp.testing.assert_allclose(got[4], [-1.0, -1.0])


def _train_embedding(sparse_grad, opt, steps=4, lr=0.2):
    mx.random.seed(7)
    vocab, dim = 24, 4
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Embedding(vocab, dim, sparse_grad=sparse_grad),
            gluon.nn.Dense(2, flatten=False, in_units=dim))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), opt,
                            {"learning_rate": lr, "wd": 0.0},
                            kvstore="device")
    rng = onp.random.RandomState(3)
    l2 = gluon.loss.L2Loss()
    for _ in range(steps):
        x = np.array(rng.randint(0, vocab, (6, 5)).astype("float32"))
        y = np.array(rng.uniform(-1, 1, (6, 5, 2)).astype("float32"))
        with autograd.record():
            loss = l2(net(x), y)
        loss.backward()
        trainer.step(6)
    return {k: A(p.data()) for k, p in net.collect_params().items()}


def test_sparse_embedding_step_matches_dense_exactly():
    """The sparse-gradient Trainer path through kvstore pushpull must land
    on the same weights as the dense SGD path — bit-for-bit (VERDICT r3
    #6; with wd=0 and no momentum, lazy row updates and the dense update
    are the same math, only the row representation differs). Runs on the
    8-device CPU mesh conftest platform. (Adam is intentionally excluded:
    lazy update skips moment decay on untouched rows BY DESIGN — the
    reference's lazy_update divergence — covered by
    `test_sparse.py::test_embedding_sparse_grad_adam_lazy_update`.)"""
    dense = _train_embedding(False, "sgd")
    sp = _train_embedding(True, "sgd")
    assert dense.keys() == sp.keys()
    for k in dense:
        onp.testing.assert_array_equal(dense[k], sp[k]), k
