"""KVStore row_sparse push/pull (reference: `src/kvstore/kvstore_local.h:232`
PushImpl row_sparse merge, `:279` PullRowSparseImpl) and the Trainer wiring
for `Embedding(sparse_grad=True)` — the BERT-scale embedding path."""
import numpy as onp

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, kvstore, np
from incubator_mxnet_tpu.ndarray import sparse


def A(x):
    return x.asnumpy()


def _rs(rows, vals, shape):
    return sparse.row_sparse_array(
        (onp.asarray(vals, "float32"), onp.asarray(rows, "int64")),
        shape=shape)


def test_push_merges_row_sparse_copies():
    """Per-device sparse gradient copies merge by gather-unique-sum and the
    store entry STAYS row_sparse."""
    kv = kvstore.create("device")
    g1 = _rs([1, 3], [[1.0, 1.0], [2.0, 2.0]], (6, 2))
    g2 = _rs([3, 4], [[10.0, 10.0], [4.0, 4.0]], (6, 2))
    kv.push("emb", [g1, g2])
    got = kv.pull("emb")
    assert got.stype == "row_sparse"
    dense = onp.zeros((6, 2), "float32")
    dense[1] = 1.0
    dense[3] = 12.0
    dense[4] = 4.0
    onp.testing.assert_allclose(A(got), dense, rtol=1e-6)
    # merged storage is canonical: unique sorted rows only
    onp.testing.assert_array_equal(A(got.indices), [1, 3, 4])


def test_push_rejects_mixed_stypes():
    import pytest

    kv = kvstore.create("local")
    g1 = _rs([0], [[1.0, 1.0]], (4, 2))
    g2 = np.zeros((4, 2))
    with pytest.raises(ValueError):
        kv.push("k", [g1, g2])


def test_row_sparse_pull_slices_rows():
    kv = kvstore.create("local")
    w = onp.random.RandomState(0).uniform(-1, 1, (8, 3)).astype("float32")
    kv.init("emb", np.array(w))
    out = kv.row_sparse_pull("emb", row_ids=np.array(
        onp.array([5, 2, 5], "float32")))
    assert out.stype == "row_sparse"
    onp.testing.assert_array_equal(A(out.indices), [2, 5])
    onp.testing.assert_allclose(A(out.data), w[[2, 5]], rtol=1e-6)
    # out= write form
    dst = sparse.zeros("row_sparse", (8, 3))
    kv.row_sparse_pull("emb", out=dst, row_ids=np.array(
        onp.array([0, 7], "float32")))
    onp.testing.assert_allclose(A(dst.data), w[[0, 7]], rtol=1e-6)


def test_pushpull_keeps_grad_sparse():
    kv = kvstore.create("device")
    g = _rs([2, 2, 5], [[1.0], [3.0], [7.0]], (6, 1))
    kv.pushpull(0, g, out=g)
    assert g.stype == "row_sparse"
    onp.testing.assert_array_equal(A(g.indices), [2, 5])
    onp.testing.assert_allclose(A(g.data), [[4.0], [7.0]], rtol=1e-6)


def test_updater_receives_sparse_and_updates_lazily():
    """push with a kvstore-side optimizer: only touched rows move
    (reference: server-side ApplyUpdates with row_sparse,
    `kvstore_dist_server.h:349`)."""
    from incubator_mxnet_tpu import optimizer

    kv = kvstore.create("local")
    w = onp.ones((5, 2), "float32")
    kv.init("emb", np.array(w))
    kv.set_optimizer(optimizer.SGD(learning_rate=1.0))
    kv.push("emb", _rs([1, 4], [[1.0, 1.0], [2.0, 2.0]], (5, 2)))
    got = A(kv.pull("emb"))
    onp.testing.assert_allclose(got[0], [1.0, 1.0])
    onp.testing.assert_allclose(got[1], [0.0, 0.0])
    onp.testing.assert_allclose(got[4], [-1.0, -1.0])


def _train_embedding(sparse_grad, opt, steps=4, lr=0.2):
    mx.random.seed(7)
    vocab, dim = 24, 4
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Embedding(vocab, dim, sparse_grad=sparse_grad),
            gluon.nn.Dense(2, flatten=False, in_units=dim))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), opt,
                            {"learning_rate": lr, "wd": 0.0},
                            kvstore="device")
    rng = onp.random.RandomState(3)
    l2 = gluon.loss.L2Loss()
    for _ in range(steps):
        x = np.array(rng.randint(0, vocab, (6, 5)).astype("float32"))
        y = np.array(rng.uniform(-1, 1, (6, 5, 2)).astype("float32"))
        with autograd.record():
            loss = l2(net(x), y)
        loss.backward()
        trainer.step(6)
    return {k: A(p.data()) for k, p in net.collect_params().items()}


def test_sparse_embedding_step_matches_dense_exactly():
    """The sparse-gradient Trainer path through kvstore pushpull must land
    on the same weights as the dense SGD path — bit-for-bit (VERDICT r3
    #6; with wd=0 and no momentum, lazy row updates and the dense update
    are the same math, only the row representation differs). Runs on the
    8-device CPU mesh conftest platform. (Adam is intentionally excluded:
    lazy update skips moment decay on untouched rows BY DESIGN — the
    reference's lazy_update divergence — covered by
    `test_sparse.py::test_embedding_sparse_grad_adam_lazy_update`.)"""
    dense = _train_embedding(False, "sgd")
    sp = _train_embedding(True, "sgd")
    assert dense.keys() == sp.keys()
    for k in dense:
        onp.testing.assert_array_equal(dense[k], sp[k]), k


# ---------------------------------------------------------------------------
# dense kvstore depth (reference: tests/python/unittest/test_kvstore.py)
# ---------------------------------------------------------------------------

def test_init_and_pull_single_key():
    kv = kvstore.create("local")
    kv.init("w", np.array(onp.full((3,), 2.0, "float32")))
    out = np.zeros((3,))
    kv.pull("w", out=out)
    onp.testing.assert_allclose(A(out), 2.0)


def test_init_list_keys():
    kv = kvstore.create("local")
    kv.init(["a", "b"], [np.ones((2,)), np.full((2,), 3.0)])
    oa, ob = np.zeros((2,)), np.zeros((2,))
    kv.pull(["a", "b"], out=[oa, ob])
    onp.testing.assert_allclose(A(oa), 1.0)
    onp.testing.assert_allclose(A(ob), 3.0)


def test_push_aggregates_copies_dense():
    kv = kvstore.create("device")
    kv.init("g", np.zeros((4,)))
    kv.push("g", [np.ones((4,)), np.full((4,), 2.0)])
    out = np.zeros((4,))
    kv.pull("g", out=out)
    onp.testing.assert_allclose(A(out), 3.0)


def test_pushpull_fused_matches_push_then_pull():
    kv = kvstore.create("device")
    g = np.array(onp.arange(4, dtype="float32"))
    out = np.zeros((4,))
    kv.pushpull("k", g, out=out)
    onp.testing.assert_allclose(A(out), A(g))


def test_pull_to_multiple_targets():
    kv = kvstore.create("local")
    kv.init("w", np.full((2,), 5.0))
    t1, t2 = np.zeros((2,)), np.zeros((2,))
    kv.pull("w", out=[t1, t2])
    onp.testing.assert_allclose(A(t1), 5.0)
    onp.testing.assert_allclose(A(t2), 5.0)


def test_updater_applied_on_push():
    kv = kvstore.create("local")
    kv.init("w", np.full((3,), 1.0))
    seen = []

    def upd(key, grad, weight):
        seen.append(key)
        weight -= 0.1 * grad

    kv.set_updater(upd)
    kv.push("w", np.full((3,), 1.0))
    out = np.zeros((3,))
    kv.pull("w", out=out)
    onp.testing.assert_allclose(A(out), 0.9, rtol=1e-6)
    assert seen == ["w"]


def test_optimizer_on_kvstore_state():
    from incubator_mxnet_tpu import optimizer

    kv = kvstore.create("local")
    kv.init("w", np.full((2,), 1.0))
    kv.set_optimizer(optimizer.SGD(learning_rate=0.5))
    kv.push("w", np.full((2,), 1.0))
    kv.push("w", np.full((2,), 1.0))
    out = np.zeros((2,))
    kv.pull("w", out=out)
    onp.testing.assert_allclose(A(out), 0.0, atol=1e-6)


def test_broadcast_writes_out():
    kv = kvstore.create("device")
    out = np.zeros((3,))
    kv.broadcast("b", np.full((3,), 4.0), out=out)
    onp.testing.assert_allclose(A(out), 4.0)


def test_save_load_optimizer_states(tmp_path):
    from incubator_mxnet_tpu import optimizer

    kv = kvstore.create("local")
    kv.init("w", np.full((2,), 1.0))
    kv.set_optimizer(optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.push("w", np.ones((2,)))
    f = str(tmp_path / "states.bin")
    kv.save_optimizer_states(f)
    kv2 = kvstore.create("local")
    cur = np.zeros((2,))
    kv.pull("w", out=cur)
    kv2.init("w", cur)          # same WEIGHT as kv (states file holds
    kv2.set_optimizer(optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(f)  # ...only the optimizer state)
    # same state => identical next update
    kv.push("w", np.ones((2,)))
    kv2.push("w", np.ones((2,)))
    o1, o2 = np.zeros((2,)), np.zeros((2,))
    kv.pull("w", out=o1)
    kv2.pull("w", out=o2)
    onp.testing.assert_allclose(A(o1), A(o2), rtol=1e-6)


def test_gradient_compression_roundtrip_error_bounded():
    kv = kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", np.zeros((64,)))
    g = np.array(onp.random.RandomState(0).uniform(
        -1, 1, (64,)).astype("float32"))
    out = np.zeros((64,))
    kv.pushpull("w", g, out=out)
    # quantized: values collapse toward {-t, 0, +t}; error feedback keeps
    # the long-run average unbiased, single-step error bounded by t
    assert onp.abs(A(out) - A(g)).max() <= 0.5 + 1e-6


def test_type_registry_create_names():
    for name in ("local", "device", "nccl", "horovod", "byteps"):
        kv = kvstore.create(name)
        assert kv is not None


def test_invalid_type_raises():
    import pytest

    with pytest.raises(Exception):
        kvstore.create("definitely_not_a_store")
