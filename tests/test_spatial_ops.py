"""Spatial-transform / flow / fft op family tests (reference model:
tests/python/unittest/test_operator.py spatial transformer & correlation
sections)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import numpy_extension as npx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def rand(*shape, seed=0):
    return onp.random.RandomState(seed).randn(*shape).astype(onp.float32)


def test_grid_generator_identity_affine():
    theta = mnp.array(onp.tile(onp.array([1, 0, 0, 0, 1, 0], onp.float32),
                               (2, 1)))
    g = npx.grid_generator(theta, "affine", (5, 7))
    assert g.shape == (2, 2, 5, 7)
    onp.testing.assert_allclose(A(g)[0, 0, 0], onp.linspace(-1, 1, 7),
                                rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(A(g)[0, 1, :, 0], onp.linspace(-1, 1, 5),
                                rtol=1e-5, atol=1e-6)


def test_grid_generator_warp_zero_flow_is_identity_grid():
    flow = mnp.zeros((1, 2, 4, 4))
    g = A(npx.grid_generator(flow, "warp"))
    onp.testing.assert_allclose(g[0, 0, 0], onp.linspace(-1, 1, 4), rtol=1e-5,
                                atol=1e-6)


def test_bilinear_sampler_identity():
    x = mnp.array(rand(2, 3, 8, 8))
    theta = mnp.array(onp.tile(onp.array([1, 0, 0, 0, 1, 0], onp.float32),
                               (2, 1)))
    g = npx.grid_generator(theta, "affine", (8, 8))
    y = npx.bilinear_sampler(x, g)
    onp.testing.assert_allclose(A(y), A(x), rtol=1e-4, atol=1e-5)


def test_bilinear_sampler_half_pixel_shift():
    """A 0.5-pixel x-shift averages horizontal neighbors."""
    x = onp.zeros((1, 1, 1, 4), onp.float32)
    x[0, 0, 0] = [0.0, 2.0, 4.0, 6.0]
    # grid: identity + shift of 0.5 px in x; w=4 → normalized shift = 1/3
    gx = onp.linspace(-1, 1, 4) + (0.5 * 2 / 3)
    g = onp.zeros((1, 2, 1, 4), onp.float32)
    g[0, 0, 0] = gx
    g[0, 1, 0] = 0.0
    y = A(npx.bilinear_sampler(mnp.array(x), mnp.array(g)))
    onp.testing.assert_allclose(y[0, 0, 0, :3], [1.0, 3.0, 5.0],
                                rtol=1e-4, atol=1e-5)


def test_spatial_transformer_downsample():
    x = mnp.array(rand(1, 2, 8, 8))
    theta = mnp.array(onp.array([[1, 0, 0, 0, 1, 0]], onp.float32))
    y = npx.spatial_transformer(x, theta, (4, 4))
    assert y.shape == (1, 2, 4, 4)


def test_spatial_transformer_grad_flows():
    x = NDArray(rand(1, 1, 6, 6))
    theta = NDArray(onp.array([[1, 0, 0, 0, 1, 0]], onp.float32))
    x.attach_grad()
    theta.attach_grad()
    with autograd.record():
        out = npx.spatial_transformer(x, theta, (3, 3))
        loss = out.sum()
    loss.backward()
    assert float(onp.abs(A(x.grad)).sum()) > 0
    assert float(onp.abs(A(theta.grad)).sum()) > 0


def test_roi_pooling_whole_image_is_global_max():
    x = mnp.array(rand(1, 2, 8, 8))
    rois = mnp.array(onp.array([[0, 0, 0, 7, 7]], onp.float32))
    y = A(npx.roi_pooling(x, rois, (1, 1)))
    # single 1x1 bin over the whole ROI ≈ global max (2x2 sample lattice
    # divergence documented) — must be within one interpolation step
    ref = A(x).max(axis=(2, 3))
    assert y.shape == (1, 2, 1, 1)
    assert (y.reshape(1, 2) <= ref + 1e-5).all()
    assert (y.reshape(1, 2) >= ref - 2.0).all()


def test_correlation_self_zero_displacement_is_mean_square():
    x = rand(1, 4, 6, 6, seed=3)
    out = A(npx.correlation(mnp.array(x), mnp.array(x), kernel_size=1,
                            max_displacement=1, pad_size=1))
    # D = 3 → 9 channels; center channel (index 4) = mean_c x*x
    assert out.shape == (1, 9, 6, 6)
    onp.testing.assert_allclose(out[0, 4], (x[0] ** 2).mean(0),
                                rtol=1e-4, atol=1e-5)


def test_correlation_shapes_reference_formula():
    x = mnp.array(rand(2, 3, 8, 8))
    out = npx.correlation(x, x, kernel_size=1, max_displacement=2,
                          stride1=1, stride2=1, pad_size=2)
    # padded 12, border 2 → 8×8 out, D=5 → 25 channels
    assert out.shape == (2, 25, 8, 8)


def test_deformable_conv_zero_offset_matches_conv():
    x = rand(2, 3, 8, 8)
    w = rand(4, 3, 3, 3, seed=1)
    off = mnp.zeros((2, 2 * 9, 6, 6))
    y = A(npx.deformable_convolution(mnp.array(x), off, mnp.array(w),
                                     kernel=(3, 3), num_filter=4))
    import jax.lax as lax
    import jax.numpy as jnp

    ref = onp.asarray(lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(0, 0), (0, 0)],
        precision="highest"))
    onp.testing.assert_allclose(y, ref, rtol=5e-2, atol=5e-2)


def test_deformable_conv_integer_offset_shifts_input():
    """Constant integer offset (dy=0, dx=1) equals conv over x shifted by 1."""
    x = rand(1, 1, 8, 8, seed=5)
    w = onp.ones((1, 1, 1, 1), onp.float32)
    off = onp.zeros((1, 2, 8, 8), onp.float32)
    off[0, 1] = 1.0  # dx = +1 for the single 1x1 tap
    y = A(npx.deformable_convolution(mnp.array(x), mnp.array(off),
                                     mnp.array(w), kernel=(1, 1),
                                     num_filter=1))
    onp.testing.assert_allclose(y[0, 0, :, :-1], x[0, 0, :, 1:],
                                rtol=1e-4, atol=1e-4)


def test_correlation_too_small_input_raises():
    x = mnp.array(rand(1, 1, 2, 2))
    with pytest.raises(ValueError, match="pad_size"):
        npx.correlation(x, x, kernel_size=1, max_displacement=2, pad_size=0)


def test_boolean_mask_length_mismatch_raises():
    d = mnp.array(rand(4, 3))
    with pytest.raises(ValueError, match="mask length"):
        npx.boolean_mask(d, mnp.array(onp.array([1, 0, 0, 0, 0, 1],
                                                onp.float32)))


def test_fft_matches_numpy():
    x = rand(3, 16, seed=2)
    out = A(npx.fft(mnp.array(x)))
    z = onp.fft.fft(x, axis=-1)
    inter = onp.stack([z.real, z.imag], -1).reshape(3, 32)
    onp.testing.assert_allclose(out, inter, rtol=1e-3, atol=1e-3)


def test_ifft_roundtrip_scaled_by_n():
    x = rand(2, 8, seed=4)
    r = A(npx.ifft(npx.fft(mnp.array(x))))
    onp.testing.assert_allclose(r, x * 8, rtol=1e-3, atol=1e-3)


def test_window_functions():
    for name in ["blackman", "hamming", "hanning", "bartlett"]:
        out = A(getattr(mnp, name)(12))
        onp.testing.assert_allclose(out, getattr(onp, name)(12),
                                    rtol=1e-5, atol=1e-6)


def test_fill_diagonal_and_diag_indices_from():
    a = mnp.zeros((4, 4))
    mnp.fill_diagonal(a, 7.0)
    onp.testing.assert_array_equal(A(a).diagonal(), onp.full(4, 7.0))
    idx = mnp.diag_indices_from(a)
    onp.testing.assert_array_equal(A(idx[0]), onp.arange(4))


def test_bilinear_sampler_zero_pads_outside():
    """Reference semantics: out-of-boundary samples contribute 0, not the
    border value (`src/operator/bilinear_sampler-inl.h`)."""
    x = mnp.ones((1, 1, 4, 4))
    theta = mnp.array(onp.array([[2, 0, 0, 0, 2, 0]], onp.float32))  # zoom out
    y = A(npx.spatial_transformer(x, theta, (4, 4)))
    assert y[0, 0, 0, 0] == 0.0   # corner maps outside → zero
    assert y[0, 0, 1, 1] > 0.0    # interior still sampled


def test_boolean_mask_forward_and_grad():
    d = NDArray(onp.arange(12, dtype=onp.float32).reshape(4, 3))
    m = mnp.array(onp.array([1, 0, 1, 0], onp.float32))
    d.attach_grad()
    with autograd.record():
        out = npx.boolean_mask(d, m)
        loss = out.sum()
    assert out.shape == (2, 3)
    loss.backward()
    g = A(d.grad)
    onp.testing.assert_array_equal(g[0], onp.ones(3))
    onp.testing.assert_array_equal(g[1], onp.zeros(3))
    onp.testing.assert_array_equal(g[2], onp.ones(3))


def test_fill_diagonal_grad_and_array_val():
    a = NDArray(onp.ones((3, 3), onp.float32))
    a.attach_grad()
    with autograd.record():
        mnp.fill_diagonal(a, 0.0)
        loss = (a * a).sum()
    loss.backward()
    g = A(a.grad)
    # diagonal was overwritten by a constant → zero grad there; 2*a elsewhere
    onp.testing.assert_allclose(g, 2 * (1 - onp.eye(3)), rtol=1e-6)
    b = mnp.zeros((3, 3))
    mnp.fill_diagonal(b, mnp.array(onp.array([1., 2., 3.], onp.float32)))
    onp.testing.assert_array_equal(A(b).diagonal(), [1., 2., 3.])


def test_deformable_conv_kernel_mismatch_raises():
    x = mnp.array(rand(1, 1, 6, 6))
    w = mnp.array(rand(1, 1, 5, 5, seed=1))
    off = mnp.zeros((1, 2 * 9, 4, 4))
    with pytest.raises(ValueError, match="disagrees"):
        npx.deformable_convolution(x, off, w, kernel=(3, 3), num_filter=1)


def test_bilinear_sampler_grad_numeric():
    """Finite-difference check on the sampler (reference discipline:
    test_utils.check_numeric_gradient)."""
    x0 = rand(1, 1, 5, 5, seed=7)
    g0 = onp.zeros((1, 2, 3, 3), onp.float32)
    g0[0, 0] = onp.linspace(-0.5, 0.5, 3)[None, :]
    g0[0, 1] = onp.linspace(-0.5, 0.5, 3)[:, None]

    def f(xv):
        return float(A(npx.bilinear_sampler(mnp.array(xv),
                                            mnp.array(g0)).sum()))

    x = NDArray(x0)
    x.attach_grad()
    with autograd.record():
        out = npx.bilinear_sampler(x, NDArray(g0)).sum()
    out.backward()
    eps = 1e-2
    rs = onp.random.RandomState(0)
    for _ in range(4):
        i = tuple(rs.randint(0, s) for s in x0.shape)
        xp = x0.copy()
        xp[i] += eps
        xm = x0.copy()
        xm[i] -= eps
        num = (f(xp) - f(xm)) / (2 * eps)
        onp.testing.assert_allclose(A(x.grad)[i], num, rtol=1e-2, atol=1e-2)
