"""Per-optimizer single-step checks against hand-computed update math
(reference: `tests/python/unittest/test_optimizer.py` — each rule's
closed-form step on a tiny weight, plus lr/wd/rescale/clip plumbing)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, optimizer
from incubator_mxnet_tpu.ndarray.ndarray import NDArray

W0 = onp.array([1.0, -2.0, 3.0], "float32")
G0 = onp.array([0.5, -0.25, 1.0], "float32")


def _step(opt, w=None, g=None, n=1):
    wv = onp.array(W0 if w is None else w)
    gv = onp.array(G0 if g is None else g)
    weight = NDArray(wv)
    state = opt.create_state(0, weight)
    for _ in range(n):
        grad = NDArray(gv)
        new_state = opt.update(0, weight, grad, state)
        if new_state is not None:
            state = new_state
    return weight.asnumpy(), state


def test_sgd_vanilla():
    got, _ = _step(optimizer.SGD(learning_rate=0.1, wd=0.0))
    onp.testing.assert_allclose(got, W0 - 0.1 * G0, rtol=1e-6)


def test_sgd_wd():
    got, _ = _step(optimizer.SGD(learning_rate=0.1, wd=0.01))
    onp.testing.assert_allclose(got, W0 - 0.1 * (G0 + 0.01 * W0),
                                rtol=1e-6)


def test_sgd_momentum_two_steps():
    opt = optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.0)
    got, _ = _step(opt, n=2)
    mom1 = -0.1 * G0
    w1 = W0 + mom1
    mom2 = 0.9 * mom1 - 0.1 * G0
    onp.testing.assert_allclose(got, w1 + mom2, rtol=1e-6)


def test_sgd_rescale_grad():
    opt = optimizer.SGD(learning_rate=0.1, wd=0.0)
    opt.rescale_grad = 0.5
    got, _ = _step(opt)
    onp.testing.assert_allclose(got, W0 - 0.1 * 0.5 * G0, rtol=1e-6)


def test_sgd_clip_gradient():
    opt = optimizer.SGD(learning_rate=1.0, wd=0.0, clip_gradient=0.3)
    got, _ = _step(opt)
    onp.testing.assert_allclose(got, W0 - onp.clip(G0, -0.3, 0.3),
                                rtol=1e-6)


def test_nag_step():
    opt = optimizer.NAG(learning_rate=0.1, momentum=0.9, wd=0.0)
    got, _ = _step(opt)
    mom = G0.copy()                       # first step: mom = g
    onp.testing.assert_allclose(
        got, W0 - 0.1 * (G0 + 0.9 * mom), rtol=1e-5)


def test_adam_first_step_is_lr_sized():
    opt = optimizer.Adam(learning_rate=0.01, wd=0.0)
    got, _ = _step(opt)
    # t=1: m̂=g, v̂=g² → update ≈ lr·sign(g)
    onp.testing.assert_allclose(got, W0 - 0.01 * onp.sign(G0), rtol=1e-3)


def test_adamw_decouples_wd():
    opt_w = optimizer.AdamW(learning_rate=0.01, wd=0.1)
    got_w, _ = _step(opt_w)
    # decoupled: w -= lr*wd*w ON TOP of the adam step (wd not in grad)
    opt0 = optimizer.AdamW(learning_rate=0.01, wd=0.0)
    got0, _ = _step(opt0)
    onp.testing.assert_allclose(got_w, got0 - 0.01 * 0.1 * W0, rtol=1e-4,
                                atol=1e-6)


def test_rmsprop_step():
    opt = optimizer.RMSProp(learning_rate=0.01, wd=0.0)
    got, _ = _step(opt)
    assert not onp.allclose(got, W0)
    assert onp.isfinite(got).all()


def test_adagrad_accumulates():
    opt = optimizer.AdaGrad(learning_rate=0.1, wd=0.0)
    got1, _ = _step(opt, n=1)
    got2, _ = _step(opt, n=2)
    # second step moves LESS per step (history grows)
    step1 = onp.abs(W0 - got1)
    step2 = onp.abs(got1 - got2)
    assert (step2 <= step1 + 1e-7).all()


def test_adadelta_runs():
    got, _ = _step(optimizer.AdaDelta(wd=0.0), n=3)
    assert onp.isfinite(got).all()
    assert not onp.allclose(got, W0)


def test_adamax_step():
    got, _ = _step(optimizer.Adamax(learning_rate=0.01, wd=0.0))
    onp.testing.assert_allclose(got, W0 - 0.01 * onp.sign(G0), rtol=1e-3)


def test_nadam_runs():
    got, _ = _step(optimizer.Nadam(learning_rate=0.01, wd=0.0), n=2)
    assert onp.isfinite(got).all()


def test_ftrl_sparsifies():
    opt = optimizer.Ftrl(learning_rate=0.5, lamda1=10.0, wd=0.0)
    got, _ = _step(opt, n=2)
    # huge l1 drives weights to exactly zero
    onp.testing.assert_allclose(got, onp.zeros_like(W0), atol=1e-6)


def test_signum_uses_sign():
    opt = optimizer.Signum(learning_rate=0.1, momentum=0.0, wd=0.0)
    got, _ = _step(opt)
    onp.testing.assert_allclose(got, W0 - 0.1 * onp.sign(G0), rtol=1e-6)


def test_lars_layerwise_scaling():
    opt = optimizer.LARS(learning_rate=0.1, wd=0.0)
    got, _ = _step(opt)
    assert onp.isfinite(got).all()
    assert not onp.allclose(got, W0)


def test_lamb_runs():
    opt = optimizer.LAMB(learning_rate=0.01, wd=0.01)
    got, _ = _step(opt, n=2)
    assert onp.isfinite(got).all()


def test_sgld_injects_noise():
    mx.random.seed(0)
    opt = optimizer.SGLD(learning_rate=0.01, wd=0.0)
    got1, _ = _step(opt)
    mx.random.seed(1)
    got2, _ = _step(opt)
    assert not onp.allclose(got1, got2)   # stochastic updates differ


def test_lr_scheduler_applied():
    from incubator_mxnet_tpu import lr_scheduler

    sched = lr_scheduler.FactorScheduler(step=1, factor=0.5, base_lr=0.2)
    opt = optimizer.SGD(learning_rate=0.2, wd=0.0, lr_scheduler=sched)
    w = NDArray(onp.array(W0))
    s = opt.create_state(0, w)
    opt.update(0, w, NDArray(onp.array(G0)), s)
    lr1_w = w.asnumpy().copy()
    exp1 = W0 - 0.2 * G0                  # num_update=1 → base lr
    onp.testing.assert_allclose(lr1_w, exp1, rtol=1e-5)


def test_lr_mult_via_param_dict():
    opt = optimizer.SGD(learning_rate=0.1, wd=0.0)
    opt.param_dict = {}
    opt.set_lr_mult({0: 0.5})
    got, _ = _step(opt)
    onp.testing.assert_allclose(got, W0 - 0.05 * G0, rtol=1e-5)


def test_wd_mult():
    opt = optimizer.SGD(learning_rate=0.1, wd=0.1)
    opt.set_wd_mult({0: 0.0})             # kill wd for this index
    got, _ = _step(opt)
    onp.testing.assert_allclose(got, W0 - 0.1 * G0, rtol=1e-5)


def test_multi_precision_fp16_master():
    import jax.numpy as jnp

    opt = optimizer.SGD(learning_rate=0.1, momentum=0.9,
                        multi_precision=True, wd=0.0)
    w16 = NDArray(jnp.asarray(W0, jnp.float16))
    state = opt.create_state_multi_precision(0, w16)
    assert isinstance(state, tuple)        # (fp32 master, inner state)
    opt.update_multi_precision(0, w16, NDArray(jnp.asarray(G0, jnp.float16)),
                               state)
    onp.testing.assert_allclose(
        onp.asarray(w16.asnumpy(), "float32"), W0 - 0.1 * G0, rtol=1e-3)


def test_create_optimizer_registry():
    for name in ("sgd", "adam", "rmsprop", "adagrad", "nag", "signum"):
        opt = optimizer.create(name, learning_rate=0.1)
        assert isinstance(opt, optimizer.Optimizer)


def test_get_updater_applies():
    opt = optimizer.SGD(learning_rate=0.1, wd=0.0)
    upd = optimizer.get_updater(opt)
    w = NDArray(onp.array(W0))
    upd(0, NDArray(onp.array(G0)), w)
    onp.testing.assert_allclose(w.asnumpy(), W0 - 0.1 * G0, rtol=1e-6)


def test_updater_states_roundtrip():
    opt = optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.0)
    upd = optimizer.get_updater(opt)
    w = NDArray(onp.array(W0))
    upd(0, NDArray(onp.array(G0)), w)
    blob = upd.get_states()
    upd2 = optimizer.get_updater(optimizer.SGD(learning_rate=0.1,
                                               momentum=0.9, wd=0.0))
    upd2.set_states(blob)
    w2 = NDArray(w.asnumpy())
    upd(0, NDArray(onp.array(G0)), w)
    upd2(0, NDArray(onp.array(G0)), w2)
    onp.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-6)


def test_num_update_counts_per_index():
    opt = optimizer.SGD(learning_rate=0.1, wd=0.0)
    _step(opt, n=3)
    assert opt.num_update == 3