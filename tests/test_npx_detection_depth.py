"""Detection / indexing / sequence op depth (reference:
`tests/python/unittest/test_operator.py` box/NMS/sequence families +
`test_numpy_op.py` indexing rows): value checks against straightforward
numpy goldens over parametrized shapes and formats."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import npx
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def _boxes(n, seed=0):
    r = onp.random.RandomState(seed)
    xy = r.uniform(0, 0.6, (n, 2)).astype(onp.float32)
    wh = r.uniform(0.1, 0.4, (n, 2)).astype(onp.float32)
    return onp.concatenate([xy, xy + wh], axis=1)          # corner format


def _iou_np(a, b):
    tl = onp.maximum(a[:, None, :2], b[None, :, :2])
    br = onp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = onp.clip(br - tl, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    ar_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    ar_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / (ar_a[:, None] + ar_b[None, :] - inter)


@pytest.mark.parametrize("na,nb", [(1, 1), (4, 6), (10, 3), (1, 8)])
def test_box_iou_corner(na, nb):
    a, b = _boxes(na, 1), _boxes(nb, 2)
    out = npx.box_iou(NDArray(a), NDArray(b), format="corner")
    onp.testing.assert_allclose(A(out), _iou_np(a, b), rtol=1e-5, atol=1e-6)


def test_box_iou_center_format_matches_corner():
    a, b = _boxes(5, 3), _boxes(4, 4)

    def to_center(x):
        ctr = (x[:, :2] + x[:, 2:]) / 2
        wh = x[:, 2:] - x[:, :2]
        return onp.concatenate([ctr, wh], 1)

    ref = A(npx.box_iou(NDArray(a), NDArray(b), format="corner"))
    out = A(npx.box_iou(NDArray(to_center(a)), NDArray(to_center(b)),
                        format="center"))
    onp.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_box_nms_suppresses_overlaps():
    # three boxes: #0 high score, #1 overlaps #0 heavily, #2 disjoint
    data = onp.array([[0.9, 0.0, 0.0, 0.5, 0.5],
                      [0.8, 0.05, 0.05, 0.55, 0.55],
                      [0.7, 0.6, 0.6, 0.9, 0.9]], onp.float32)[None]
    out = A(npx.box_nms(NDArray(data), overlap_thresh=0.5,
                        score_index=0, coord_start=1))
    kept_scores = sorted(s for s in out[0, :, 0].tolist() if s > 0)
    assert kept_scores == pytest.approx([0.7, 0.9])


@pytest.mark.parametrize("depth", [3, 7])
@pytest.mark.parametrize("shape", [(4,), (2, 3)])
def test_one_hot_shapes(shape, depth):
    r = onp.random.RandomState(0)
    idx = r.randint(0, depth, shape).astype(onp.int32)
    out = A(npx.one_hot(NDArray(idx), depth))
    ref = onp.eye(depth, dtype=onp.float32)[idx]
    onp.testing.assert_array_equal(out, ref)


@pytest.mark.parametrize("axis", [0, 1, -1])
def test_pick_axes(axis):
    r = onp.random.RandomState(1)
    x = r.uniform(-1, 1, (4, 5)).astype(onp.float32)
    n = x.shape[axis]
    idx = r.randint(0, n, (x.shape[1 - (axis % 2)],)).astype(onp.int32)
    out = A(npx.pick(NDArray(x), NDArray(idx), axis=axis))
    ref = onp.take_along_axis(
        x, onp.expand_dims(idx, axis % 2), axis % 2).squeeze(axis % 2)
    onp.testing.assert_allclose(out, ref, rtol=1e-6)


@pytest.mark.parametrize("k", [1, 3])
@pytest.mark.parametrize("ret_typ", ["value", "indices"])
def test_topk(k, ret_typ):
    r = onp.random.RandomState(2)
    x = r.uniform(-1, 1, (3, 8)).astype(onp.float32)
    out = A(npx.topk(NDArray(x), k=k, ret_typ=ret_typ, axis=-1))
    order = onp.argsort(-x, axis=-1)[:, :k]
    if ret_typ == "indices":
        onp.testing.assert_array_equal(out.astype(onp.int64), order)
    else:
        ref = onp.take_along_axis(x, order, -1)
        onp.testing.assert_allclose(out, ref, rtol=1e-6)


@pytest.mark.parametrize("use_len", [True, False])
def test_sequence_reverse(use_len):
    r = onp.random.RandomState(3)
    x = r.uniform(-1, 1, (4, 2, 3)).astype(onp.float32)   # (T, N, C)
    if use_len:
        lens = NDArray(onp.array([2, 4], onp.int32))
        out = A(npx.sequence_reverse(NDArray(x), lens,
                                     use_sequence_length=True))
        ref = x.copy()
        ref[:2, 0] = x[:2, 0][::-1]
        ref[:, 1] = x[:, 1][::-1]
    else:
        out = A(npx.sequence_reverse(NDArray(x)))
        ref = x[::-1]
    onp.testing.assert_allclose(out, ref, rtol=1e-6)


def test_sequence_last_with_lengths():
    r = onp.random.RandomState(4)
    x = r.uniform(-1, 1, (5, 3, 2)).astype(onp.float32)
    lens = NDArray(onp.array([1, 3, 5], onp.int32))
    out = A(npx.sequence_last(NDArray(x), lens, use_sequence_length=True))
    ref = onp.stack([x[0, 0], x[2, 1], x[4, 2]])
    onp.testing.assert_allclose(out, ref, rtol=1e-6)


@pytest.mark.parametrize("shape,idx_shape", [((4, 3), (2, 5)),
                                             ((2, 3, 4), (1, 6))])
def test_gather_nd(shape, idx_shape):
    r = onp.random.RandomState(5)
    x = r.uniform(-1, 1, shape).astype(onp.float32)
    idx = r.randint(0, shape[0], idx_shape).astype(onp.int32)
    out = A(npx.gather_nd(NDArray(x), NDArray(idx)))
    ref = x[tuple(idx)]
    onp.testing.assert_allclose(out, ref, rtol=1e-6)


def test_batch_take():
    r = onp.random.RandomState(6)
    x = r.uniform(-1, 1, (4, 5)).astype(onp.float32)
    idx = r.randint(0, 5, (4,)).astype(onp.int32)
    out = A(npx.batch_take(NDArray(x), NDArray(idx)))
    ref = x[onp.arange(4), idx]
    onp.testing.assert_allclose(out, ref, rtol=1e-6)


@pytest.mark.parametrize("clip", [True, False])
def test_box_encode_decode_roundtrip(clip):
    anchors = _boxes(6, 7)[None]
    r = onp.random.RandomState(8)
    refs = _boxes(6, 9)[None]
    means = (0.0, 0.0, 0.0, 0.0)
    stds = (0.1, 0.1, 0.2, 0.2)
    samples = onp.ones((1, 6), onp.float32)
    matches = onp.arange(6, dtype=onp.int32).reshape(1, 6)
    targets, masks = npx.box_encode(
        NDArray(samples), NDArray(matches.astype(onp.float32)),
        NDArray(anchors), NDArray(refs), means=means, stds=stds)
    decoded = npx.box_decode(targets, NDArray(anchors), std0=stds[0],
                             std1=stds[1], std2=stds[2], std3=stds[3],
                             clip=-1.0 if not clip else 1.5,
                             format="corner")
    onp.testing.assert_allclose(A(decoded)[0], refs[0], rtol=1e-3, atol=2e-3)
    del r
