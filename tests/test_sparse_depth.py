"""Sparse compute depth (reference strategy: `test_sparse_operator.py` —
stype-preserving unary/binary ops, the dot family of
`src/operator/tensor/dot-inl.h`, sparse reductions, csr slicing)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu.ndarray import NDArray, sparse


def A(x):
    return onp.asarray(x.asnumpy() if hasattr(x, "asnumpy") else x)


@pytest.fixture
def mats():
    rng = onp.random.RandomState(7)
    d1 = rng.randn(6, 5).astype("float32") * (rng.rand(6, 5) < 0.4)
    d2 = rng.randn(6, 5).astype("float32") * (rng.rand(6, 5) < 0.4)
    return d1, d2


# -- elementwise binary ------------------------------------------------------

def test_csr_add_subtract_stay_csr(mats):
    d1, d2 = mats
    c1, c2 = sparse.csr_matrix(d1), sparse.csr_matrix(d2)
    for fn, ref in ((sparse.add, d1 + d2), (sparse.subtract, d1 - d2)):
        out = fn(c1, c2)
        assert out.stype == "csr"
        onp.testing.assert_allclose(A(out), ref, rtol=1e-6)


def test_csr_multiply_intersection(mats):
    d1, d2 = mats
    out = sparse.multiply(sparse.csr_matrix(d1), sparse.csr_matrix(d2))
    assert out.stype == "csr"
    onp.testing.assert_allclose(A(out), d1 * d2, rtol=1e-6)


def test_rsp_multiply_intersection(mats):
    d1, d2 = mats
    out = sparse.multiply(sparse.row_sparse_array(d1),
                          sparse.row_sparse_array(d2))
    assert out.stype == "row_sparse"
    onp.testing.assert_allclose(A(out), d1 * d2, rtol=1e-6)


def test_scalar_mul_div_keep_structure(mats):
    d1, _ = mats
    c = sparse.csr_matrix(d1)
    out = sparse.multiply(c, 3.0)
    assert out.stype == "csr"
    onp.testing.assert_allclose(A(out), d1 * 3.0, rtol=1e-6)
    out = sparse.divide(c, 2.0)
    assert out.stype == "csr"
    onp.testing.assert_allclose(A(out), d1 / 2.0, rtol=1e-6)
    # scalar / sparse divides the implicit zeros -> dense fallback
    out = sparse.divide(2.0, c)
    assert not isinstance(out, sparse.CSRNDArray)


def test_sparse_add_n(mats):
    d1, d2 = mats
    r1, r2 = sparse.row_sparse_array(d1), sparse.row_sparse_array(d2)
    out = sparse.add_n(r1, r2, r1)
    assert out.stype == "row_sparse"
    onp.testing.assert_allclose(A(out), 2 * d1 + d2, rtol=1e-6)


# -- zero-preserving unary ---------------------------------------------------

@pytest.mark.parametrize("name,ref_fn", [
    ("abs", onp.abs), ("sign", onp.sign), ("square", onp.square),
    ("relu", lambda x: onp.maximum(x, 0)), ("negative", onp.negative),
    ("floor", onp.floor), ("ceil", onp.ceil), ("rint", onp.rint),
    ("sin", onp.sin), ("tanh", onp.tanh), ("arctan", onp.arctan),
    ("expm1", onp.expm1),
])
def test_unary_preserves_storage(mats, name, ref_fn):
    d1, _ = mats
    for make, stype in ((sparse.csr_matrix, "csr"),
                        (sparse.row_sparse_array, "row_sparse")):
        out = getattr(sparse, name)(make(d1))
        assert out.stype == stype
        onp.testing.assert_allclose(A(out), ref_fn(d1), rtol=1e-5, atol=1e-6)


def test_clip_sparse_when_zero_fixed(mats):
    d1, _ = mats
    c = sparse.csr_matrix(d1)
    out = sparse.clip(c, -0.5, 0.5)
    assert out.stype == "csr"
    onp.testing.assert_allclose(A(out), onp.clip(d1, -0.5, 0.5), rtol=1e-6)
    # range excluding zero must densify (implicit zeros clip to a_min)
    out = sparse.clip(c, 0.1, 0.5)
    assert not isinstance(out, sparse.CSRNDArray)
    onp.testing.assert_allclose(A(out), onp.clip(d1, 0.1, 0.5), rtol=1e-6)


# -- dot family --------------------------------------------------------------

def test_dot_csr_dense(mats):
    d1, _ = mats
    rhs = onp.random.RandomState(1).randn(5, 3).astype("float32")
    out = sparse.dot(sparse.csr_matrix(d1), NDArray(rhs))
    onp.testing.assert_allclose(A(out), d1 @ rhs, rtol=1e-5)


def test_dot_csrT_dense_rsp_output(mats):
    """DotCsrDnsRspImpl: csr.T @ dense emits row_sparse whose stored rows
    are the csr's live columns (the embedding-gradient shape)."""
    d1, _ = mats
    rhs = onp.random.RandomState(2).randn(6, 4).astype("float32")
    out = sparse.dot(sparse.csr_matrix(d1), NDArray(rhs),
                     transpose_a=True, forward_stype="row_sparse")
    assert out.stype == "row_sparse"
    onp.testing.assert_allclose(A(out), d1.T @ rhs, rtol=1e-5)
    live_cols = set(onp.nonzero(d1.any(axis=0))[0].tolist())
    assert set(A(out.indices).tolist()) <= live_cols | set()


def test_dot_dense_csr(mats):
    d1, _ = mats
    lhs = onp.random.RandomState(3).randn(4, 6).astype("float32")
    out = sparse.dot(NDArray(lhs), sparse.csr_matrix(d1))
    onp.testing.assert_allclose(A(out), lhs @ d1, rtol=1e-5)


# -- csr slicing -------------------------------------------------------------

def test_csr_row_slice_structural(mats):
    d1, _ = mats
    c = sparse.csr_matrix(d1)
    s = c[1:4]
    assert isinstance(s, sparse.CSRNDArray)
    assert s.shape == (3, 5)
    onp.testing.assert_allclose(A(s), d1[1:4], rtol=1e-6)
    row = c[2]
    assert isinstance(row, sparse.CSRNDArray)
    onp.testing.assert_allclose(A(row), d1[2:3], rtol=1e-6)


# -- reductions --------------------------------------------------------------

def test_csr_reductions(mats):
    d1, _ = mats
    c = sparse.csr_matrix(d1)
    onp.testing.assert_allclose(A(sparse.sum(c)), d1.sum(), rtol=1e-5)
    onp.testing.assert_allclose(A(sparse.sum(c, axis=0)), d1.sum(0), rtol=1e-5)
    onp.testing.assert_allclose(A(sparse.sum(c, axis=1)), d1.sum(1), rtol=1e-5)
    onp.testing.assert_allclose(A(sparse.sum(c, axis=1, keepdims=True)),
                                d1.sum(1, keepdims=True), rtol=1e-5)
    onp.testing.assert_allclose(A(sparse.mean(c, axis=0)), d1.mean(0),
                                rtol=1e-5)
    onp.testing.assert_allclose(A(sparse.norm(c)), onp.linalg.norm(d1),
                                rtol=1e-5)


def test_square_sum_rsp(mats):
    d1, _ = mats
    r = sparse.row_sparse_array(d1)
    out = sparse.square_sum(r, axis=1, keepdims=True)
    assert out.stype == "row_sparse"
    onp.testing.assert_allclose(A(out), (d1 ** 2).sum(1, keepdims=True),
                                rtol=1e-5)
    onp.testing.assert_allclose(A(sparse.square_sum(r)), (d1 ** 2).sum(),
                                rtol=1e-5)


def test_where_csr_condition(mats):
    d1, d2 = mats
    out = sparse.where(sparse.csr_matrix(d1), NDArray(d2), NDArray(d1))
    onp.testing.assert_allclose(A(out), onp.where(d1 != 0, d2, d1), rtol=1e-6)
