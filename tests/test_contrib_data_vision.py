"""gluon.contrib.data.vision tests (reference model:
tests/python/unittest/test_gluon_data.py + contrib dataloader tests)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import gluon, image, recordio


def _make_rec(tmp_path, n=12, size=16):
    """Pack n synthetic images into a .rec with labels i%3."""
    path = str(tmp_path / "imgs.rec")
    w = recordio.MXRecordIO(path, "w")
    rng = onp.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3)).astype(onp.uint8)
        payload = image.imencode(img)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 3), i, 0),
                              payload))
    w.close()
    return path


def test_image_record_dataset(tmp_path):
    rec = _make_rec(tmp_path)
    ds = gluon.data.vision.ImageRecordDataset(rec)
    assert len(ds) == 12
    img, label = ds[5]
    assert img.shape == (16, 16, 3)
    assert label == 5 % 3


def test_random_crop_transform():
    t = gluon.data.vision.transforms.RandomCrop(8)
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    x = NDArray(onp.zeros((16, 16, 3), onp.float32))
    out = t(x)
    assert out.shape == (8, 8, 3)
    # smaller than crop: resized up
    small = NDArray(onp.zeros((4, 4, 3), onp.float32))
    assert t(small).shape == (8, 8, 3)


def test_create_image_augment_compose():
    aug = gluon.contrib.data.vision.create_image_augment(
        (3, 8, 8), rand_mirror=True, brightness=0.1,
        mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    x = NDArray(onp.random.RandomState(0)
                .randint(0, 255, (16, 16, 3)).astype(onp.uint8))
    out = aug(x)
    assert out.shape == (3, 8, 8)  # ToTensor → CHW float


def test_create_image_augment_rejects_unsupported():
    with pytest.raises(ValueError, match="not supported"):
        gluon.contrib.data.vision.create_image_augment((3, 8, 8),
                                                       pca_noise=0.1)


def test_image_dataloader_end_to_end(tmp_path):
    rec = _make_rec(tmp_path)
    loader = gluon.contrib.data.vision.ImageDataLoader(
        batch_size=4, data_shape=(3, 8, 8), path_imgrec=rec,
        shuffle=True, rand_mirror=True)
    batches = list(loader)
    assert len(batches) == 3
    data, label = batches[0]
    assert data.shape == (4, 3, 8, 8)
    assert label.shape == (4,)
