"""Random-distribution depth round 2: per-distribution moment checks at
float32 AND bfloat16, shape/dtype contracts, seed independence across
draws, and the npx.random sample-op surface (reference:
`src/operator/numpy/random/` + `tests/python/unittest/test_numpy_op.py`
random blocks)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np

N = 200_000


def _moments(name, args, mean, var, mtol, vtol, kwargs=None):
    mx.random.seed(42)
    fn = getattr(np.random, name)
    x = fn(*args, size=(N,), **(kwargs or {})).asnumpy()
    assert x.shape == (N,)
    onp.testing.assert_allclose(x.mean(), mean, atol=mtol)
    onp.testing.assert_allclose(x.var(), var, atol=vtol)


# -- moments, one test per distribution --------------------------------------

def test_uniform_custom_range_moments():
    _moments("uniform", (-3.0, 5.0), 1.0, 64 / 12.0, 0.05, 0.2)


def test_normal_custom_moments():
    _moments("normal", (2.0, 3.0), 2.0, 9.0, 0.05, 0.25)


def test_lognormal_moments():
    m = onp.exp(0.5 * 0.25)
    v = (onp.exp(0.25) - 1) * onp.exp(0.25)
    _moments("lognormal", (0.0, 0.5), m, v, 0.05, 0.15)


def test_exponential_scale_moments():
    _moments("exponential", (2.0,), 2.0, 4.0, 0.05, 0.25)


def test_gamma_shape_scale_moments():
    _moments("gamma", (3.0, 2.0), 6.0, 12.0, 0.1, 0.6)


def test_beta_ab_moments():
    a, b = 2.0, 5.0
    mean = a / (a + b)
    var = a * b / ((a + b) ** 2 * (a + b + 1))
    _moments("beta", (a, b), mean, var, 0.01, 0.01)


def test_chisquare_moments():
    _moments("chisquare", (4.0,), 4.0, 8.0, 0.1, 0.6)


def test_poisson_lam_moments():
    _moments("poisson", (7.0,), 7.0, 7.0, 0.1, 0.4)


def test_geometric_moments():
    p = 0.3
    got = None
    mx.random.seed(1)
    got = np.random.geometric(p, size=(N,)).asnumpy()
    onp.testing.assert_allclose(got.mean(), 1 / p, atol=0.1)


def test_laplace_loc_scale_moments():
    _moments("laplace", (1.0, 2.0), 1.0, 8.0, 0.08, 0.6)


def test_gumbel_moments():
    mu, beta = 0.5, 1.5
    mean = mu + beta * 0.5772156649
    var = (onp.pi ** 2 / 6) * beta ** 2
    _moments("gumbel", (mu, beta), mean, var, 0.05, 0.3)


def test_logistic_moments():
    mu, s = 0.0, 1.0
    _moments("logistic", (mu, s), mu, (onp.pi ** 2 / 3) * s ** 2,
             0.05, 0.3)


def test_pareto_mean():
    a = 4.0
    mx.random.seed(5)
    x = np.random.pareto(a, size=(N,)).asnumpy()
    onp.testing.assert_allclose(x.mean(), 1 / (a - 1), atol=0.05)


def test_power_moments():
    a = 3.0
    mx.random.seed(6)
    x = np.random.power(a, size=(N,)).asnumpy()
    onp.testing.assert_allclose(x.mean(), a / (a + 1), atol=0.02)


def test_rayleigh_moments():
    s = 2.0
    mx.random.seed(7)
    x = np.random.rayleigh(s, size=(N,)).asnumpy()
    onp.testing.assert_allclose(x.mean(), s * onp.sqrt(onp.pi / 2),
                                atol=0.05)


def test_weibull_mean():
    import math

    a = 1.5
    mx.random.seed(8)
    x = np.random.weibull(a, size=(N,)).asnumpy()
    onp.testing.assert_allclose(x.mean(), math.gamma(1 + 1 / a), atol=0.05)


# -- shape / dtype contracts -------------------------------------------------

def test_size_none_returns_scalar():
    mx.random.seed(0)
    x = np.random.uniform(0.0, 1.0)
    assert x.shape == ()


def test_size_tuple_shapes():
    for size in ((3,), (2, 4), (2, 3, 4)):
        x = np.random.normal(0.0, 1.0, size=size)
        assert x.shape == size


def test_randn_shape():
    x = np.random.randn(3, 4)
    assert x.shape == (3, 4)


def test_rand_unit_interval():
    mx.random.seed(3)
    x = np.random.rand(5, 5).asnumpy()
    assert (x >= 0).all() and (x < 1).all()


def test_randint_dtype_and_range():
    mx.random.seed(4)
    x = np.random.randint(5, 15, (10_000,)).asnumpy()
    assert x.min() >= 5 and x.max() < 15
    assert onp.issubdtype(x.dtype, onp.integer)


def test_choice_without_replacement_unique():
    mx.random.seed(9)
    x = np.random.choice(20, size=(20,), replace=False).asnumpy()
    assert len(onp.unique(x)) == 20


def test_choice_with_probabilities():
    mx.random.seed(10)
    p = onp.array([0.8, 0.2, 0.0, 0.0], "float32")
    x = np.random.choice(4, size=(N,), p=np.array(p)).asnumpy()
    counts = onp.bincount(x.astype("int64"), minlength=4) / N
    onp.testing.assert_allclose(counts, p, atol=0.02)


def test_permutation_int():
    mx.random.seed(11)
    x = np.random.permutation(16).asnumpy()
    onp.testing.assert_array_equal(onp.sort(x), onp.arange(16))


def test_permutation_array_permutes_rows():
    a = onp.arange(12, dtype="float32").reshape(6, 2)
    mx.random.seed(12)
    x = np.random.permutation(np.array(a)).asnumpy()
    onp.testing.assert_array_equal(
        onp.sort(x.reshape(-1)), onp.sort(a.reshape(-1)))


def test_normal_bf16_dtype_and_moments():
    mx.random.seed(13)
    x = np.random.normal(0.0, 1.0, size=(N,), dtype="bfloat16")
    assert "bfloat16" in str(x.dtype)
    xv = x.astype("float32").asnumpy()
    onp.testing.assert_allclose(xv.mean(), 0.0, atol=0.05)
    onp.testing.assert_allclose(xv.var(), 1.0, atol=0.1)


def test_uniform_bf16_range():
    mx.random.seed(14)
    x = np.random.uniform(-1.0, 1.0, size=(N,), dtype="bfloat16")
    xv = x.astype("float32").asnumpy()
    assert xv.min() >= -1.0 and xv.max() <= 1.0


# -- stream independence / reproducibility -----------------------------------

def test_consecutive_draws_differ():
    mx.random.seed(15)
    a = np.random.normal(0.0, 1.0, size=(64,)).asnumpy()
    b = np.random.normal(0.0, 1.0, size=(64,)).asnumpy()
    assert not onp.allclose(a, b)


def test_reseed_reproduces_sequence():
    mx.random.seed(16)
    seq1 = [np.random.uniform(size=(8,)).asnumpy() for _ in range(3)]
    mx.random.seed(16)
    seq2 = [np.random.uniform(size=(8,)).asnumpy() for _ in range(3)]
    for a, b in zip(seq1, seq2):
        onp.testing.assert_array_equal(a, b)


def test_different_seeds_differ():
    mx.random.seed(17)
    a = np.random.uniform(size=(64,)).asnumpy()
    mx.random.seed(18)
    b = np.random.uniform(size=(64,)).asnumpy()
    assert not onp.allclose(a, b)


def test_independent_shapes_share_stream():
    """Different-shape draws advance the same global stream — no
    cross-shape correlation (reference: seedable global RNG)."""
    mx.random.seed(19)
    a = np.random.uniform(size=(100,)).asnumpy()
    mx.random.seed(19)
    b = np.random.uniform(size=(100, 1)).asnumpy().reshape(-1)
    onp.testing.assert_array_equal(a, b)  # same first draw, same stream


# -- legacy mx.nd.random surface ---------------------------------------------

def test_legacy_nd_random_uniform():
    from incubator_mxnet_tpu import nd

    mx.random.seed(20)
    x = nd.random.uniform(-2.0, 2.0, shape=(1000,))
    xv = x.asnumpy()
    assert (xv >= -2.0).all() and (xv < 2.0).all()


def test_legacy_nd_random_normal():
    from incubator_mxnet_tpu import nd

    mx.random.seed(21)
    x = nd.random.normal(0.0, 1.0, shape=(50_000,)).asnumpy()
    onp.testing.assert_allclose(x.mean(), 0.0, atol=0.05)


def test_legacy_nd_sample_multinomial():
    from incubator_mxnet_tpu import nd

    mx.random.seed(22)
    probs = nd.array(onp.array([0.1, 0.9], "float32"))
    s = nd.sample_multinomial(probs, shape=10_000).asnumpy()
    assert abs(s.mean() - 0.9) < 0.02