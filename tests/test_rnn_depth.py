"""RNN depth: fused layers vs cell unrolls, state shapes/carry,
bidirectional concat, layouts, grads (reference:
`tests/python/unittest/test_gluon_rnn.py`)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np
from incubator_mxnet_tpu.gluon import rnn

RNG = onp.random.RandomState(53)

T, N, I, H = 5, 3, 4, 6


def _x(layout="TNC"):
    shape = (T, N, I) if layout == "TNC" else (N, T, I)
    return np.array(RNG.uniform(-1, 1, shape).astype("float32"))


# -- fused layers ------------------------------------------------------------

def test_rnn_layer_output_shape():
    l = rnn.RNN(H, 1)
    l.initialize()
    out = l(_x())
    assert out.shape == (T, N, H)


def test_lstm_layer_output_and_state():
    l = rnn.LSTM(H, 1)
    l.initialize()
    x = _x()
    s0 = l.begin_state(batch_size=N)
    out, s = l(x, s0)
    assert out.shape == (T, N, H)
    assert s[0].shape == (1, N, H) and s[1].shape == (1, N, H)


def test_gru_layer_output():
    l = rnn.GRU(H, 1)
    l.initialize()
    assert l(_x()).shape == (T, N, H)


def test_two_layer_stack_shapes():
    l = rnn.LSTM(H, 2)
    l.initialize()
    s0 = l.begin_state(batch_size=N)
    out, s = l(_x(), s0)
    assert out.shape == (T, N, H)
    assert s[0].shape == (2, N, H)


def test_bidirectional_doubles_features():
    l = rnn.LSTM(H, 1, bidirectional=True)
    l.initialize()
    out = l(_x())
    assert out.shape == (T, N, 2 * H)


def test_nTC_layout():
    l = rnn.LSTM(H, 1, layout="NTC")
    l.initialize()
    out = l(_x("NTC"))
    assert out.shape == (N, T, H)


def test_state_carry_changes_output():
    l = rnn.LSTM(H, 1)
    l.initialize()
    x = _x()
    s0 = l.begin_state(batch_size=N)
    out1, s1 = l(x, s0)
    out2, _ = l(x, s1)          # different initial state → different out
    assert not onp.allclose(out1.asnumpy(), out2.asnumpy())


def test_fused_lstm_grads_flow():
    l = rnn.LSTM(H, 1)
    l.initialize()
    x = _x()
    x.attach_grad()
    with autograd.record():
        y = l(x).sum()
    y.backward()
    g = x.grad.asnumpy()
    assert g.shape == x.shape and onp.abs(g).sum() > 0


# -- cells -------------------------------------------------------------------

def test_lstm_cell_single_step():
    c = rnn.LSTMCell(H, input_size=I)
    c.initialize()
    x = np.array(RNG.uniform(-1, 1, (N, I)).astype("float32"))
    s = c.begin_state(batch_size=N)
    out, s2 = c(x, s)
    assert out.shape == (N, H)
    assert len(s2) == 2


def test_gru_cell_single_step():
    c = rnn.GRUCell(H, input_size=I)
    c.initialize()
    x = np.array(RNG.uniform(-1, 1, (N, I)).astype("float32"))
    out, s2 = c(x, c.begin_state(batch_size=N))
    assert out.shape == (N, H)
    assert len(s2) == 1


def test_rnn_cell_tanh_formula():
    c = rnn.RNNCell(H, input_size=I, activation="tanh")
    c.initialize()
    x = np.array(RNG.uniform(-1, 1, (N, I)).astype("float32"))
    s = c.begin_state(batch_size=N)
    out, _ = c(x, s)
    i2h_w = c.i2h_weight.data().asnumpy()
    i2h_b = c.i2h_bias.data().asnumpy()
    h2h_w = c.h2h_weight.data().asnumpy()
    h2h_b = c.h2h_bias.data().asnumpy()
    ref = onp.tanh(x.asnumpy() @ i2h_w.T + i2h_b
                   + s[0].asnumpy() @ h2h_w.T + h2h_b)
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_cell_unroll_matches_manual_loop():
    c = rnn.LSTMCell(H, input_size=I)
    c.initialize()
    x = _x()
    outs, state = c.unroll(T, x, layout="TNC", merge_outputs=True)
    s = c.begin_state(batch_size=N)
    manual = []
    for t in range(T):
        o, s = c(x[t], s)
        manual.append(o.asnumpy())
    onp.testing.assert_allclose(outs.asnumpy(), onp.stack(manual),
                                rtol=1e-5, atol=1e-6)


def test_sequential_rnn_cell_stack():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(H, input_size=I))
    stack.add(rnn.LSTMCell(H, input_size=H))
    stack.initialize()
    outs, _ = stack.unroll(T, _x(), layout="TNC", merge_outputs=True)
    assert outs.shape == (T, N, H)


def test_dropout_cell_eval_identity():
    c = rnn.DropoutCell(0.5)
    x = np.array(RNG.uniform(-1, 1, (N, I)).astype("float32"))
    out, _ = c(x, [])
    onp.testing.assert_array_equal(out.asnumpy(), x.asnumpy())


def test_zoneout_cell_wraps():
    base = rnn.GRUCell(H, input_size=I)
    c = rnn.ZoneoutCell(base, zoneout_states=0.1)
    c.initialize()
    x = np.array(RNG.uniform(-1, 1, (N, I)).astype("float32"))
    out, _ = c(x, c.begin_state(batch_size=N))
    assert out.shape == (N, H)


def test_residual_cell_adds_input():
    base = rnn.RNNCell(I, input_size=I)   # same width for the residual
    c = rnn.ResidualCell(base)
    c.initialize()
    x = np.array(RNG.uniform(-1, 1, (N, I)).astype("float32"))
    s = c.begin_state(batch_size=N)
    out, _ = c(x, s)
    inner, _ = base(x, base.begin_state(batch_size=N))
    onp.testing.assert_allclose(out.asnumpy(),
                                inner.asnumpy() + x.asnumpy(), rtol=1e-5)


def test_bidirectional_cell_concat():
    l = rnn.BidirectionalCell(rnn.GRUCell(H, input_size=I),
                              rnn.GRUCell(H, input_size=I))
    l.initialize()
    outs, _ = l.unroll(T, _x(), layout="TNC", merge_outputs=True)
    assert outs.shape == (T, N, 2 * H)


def test_cell_reset_clears_counters():
    c = rnn.LSTMCell(H, input_size=I)
    c.initialize()
    c.unroll(T, _x(), layout="TNC")
    c.reset()
    outs, _ = c.unroll(T, _x(), layout="TNC", merge_outputs=True)
    assert outs.shape == (T, N, H)


def test_fused_vs_cell_parity_rnn_relu():
    """Single-layer relu RNN: fused layer output == cell unroll with the
    SAME weights (the reference's fused-kernel-vs-cell contract)."""
    mx.random.seed(7)
    layer = rnn.RNN(H, 1, activation="relu")
    layer.initialize()
    x = _x()
    fused = layer(x).asnumpy()

    cell = rnn.RNNCell(H, input_size=I, activation="relu")
    cell.initialize()
    # pack the CELL's weights into the fused layer's flat vector layout
    # (w_i2h.ravel() + w_h2h.ravel() then b_i2h + b_h2h — the layout
    # _unpack_rnn_params parses)
    packed = onp.concatenate([
        cell.i2h_weight.data().asnumpy().ravel(),
        cell.h2h_weight.data().asnumpy().ravel(),
        cell.i2h_bias.data().asnumpy(),
        cell.h2h_bias.data().asnumpy()])
    layer.parameters.set_data(np.array(packed.astype("float32")))
    fused = layer(x).asnumpy()
    outs, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
    onp.testing.assert_allclose(outs.asnumpy(), fused, rtol=1e-4,
                                atol=1e-5)


def test_train_step_reduces_loss():
    mx.random.seed(1)
    from incubator_mxnet_tpu import gluon, optimizer
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    net = gluon.nn.HybridSequential()

    class Tail(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.l = rnn.LSTM(H, 1)
            self.out = gluon.nn.Dense(1)

        def forward(self, x):
            y = self.l(x)
            return self.out(y[-1])

    net = Tail()
    net.initialize()
    x = _x()
    net(x)                      # resolve deferred shapes before tracing
    l2 = gluon.loss.L2Loss()
    dp = DataParallel(net, lambda o, y: l2(o, y).mean(),
                      optimizer.Adam(learning_rate=1e-2))
    y = np.array(RNG.uniform(-1, 1, (N, 1)).astype("float32"))
    first = float(dp.step(x, y).asnumpy())
    for _ in range(15):
        last = float(dp.step(x, y).asnumpy())
    assert last < first, (first, last)