"""End-to-end accuracy validation (VERDICT r2 item 2; reference discipline:
`example/quantization/README.md:113-121` — FP32 accuracy table + ≤0.5%
INT8 top-1 drop).

No-egress substitute for ImageNet/MNIST files: the sklearn `load_digits`
corpus — 1797 REAL handwritten digit scans (8×8, the UCI test partition of
NIST) — written to disk in the actual idx-ubyte format and read back
through the `MNISTIter` facade, so the full file→iterator→train→accuracy
path is exercised on real image data, not synthetic tensors.

Thresholds: the reference's MNIST MLP tutorial trains to ≥97%
(`example/gluon/mnist/mnist.py` --epochs 10 reaches ~98%); digits is an
easier corpus, same bar. INT8 drop bound is the reference's ≤0.5% top-1.
"""
import gzip
import os
import struct

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np
from incubator_mxnet_tpu.contrib import quantization as q
from incubator_mxnet_tpu.io import MNISTIter

sklearn = pytest.importorskip("sklearn")
from sklearn.datasets import load_digits  # noqa: E402


def _write_idx_images(path, arr, gz=False):
    """idx3-ubyte writer (the format `src/io/iter_mnist.cc` parses)."""
    op = gzip.open if gz else open
    with op(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">III", *arr.shape))
        f.write(arr.astype(onp.uint8).tobytes())


def _write_idx_labels(path, arr, gz=False):
    op = gzip.open if gz else open
    with op(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 1))
        f.write(struct.pack(">I", arr.shape[0]))
        f.write(arr.astype(onp.uint8).tobytes())


@pytest.fixture(scope="module")
def digits_idx(tmp_path_factory):
    """Real handwritten digits split 80/20 and written as idx files —
    train images gzipped to cover both reader branches."""
    d = load_digits()
    images = (d.images * (255.0 / 16.0)).astype(onp.uint8)  # (N, 8, 8)
    labels = d.target.astype(onp.uint8)
    rng = onp.random.RandomState(0)
    perm = rng.permutation(len(images))
    images, labels = images[perm], labels[perm]
    n_tr = int(0.8 * len(images))
    root = tmp_path_factory.mktemp("digits")
    paths = {
        "train_images": str(root / "train-images-idx3-ubyte.gz"),
        "train_labels": str(root / "train-labels-idx1-ubyte"),
        "test_images": str(root / "t10k-images-idx3-ubyte"),
        "test_labels": str(root / "t10k-labels-idx1-ubyte"),
    }
    _write_idx_images(paths["train_images"], images[:n_tr], gz=True)
    _write_idx_labels(paths["train_labels"], labels[:n_tr])
    _write_idx_images(paths["test_images"], images[n_tr:])
    _write_idx_labels(paths["test_labels"], labels[n_tr:])
    return paths


def _accuracy(net, x, y, bs=256):
    correct = 0
    for i in range(0, len(x), bs):
        out = net(np.array(x[i:i + bs]))
        correct += int((out.asnumpy().argmax(1) == y[i:i + bs]).sum())
    return correct / len(x)


def test_mlp_trains_to_97pct_via_mnistiter(digits_idx):
    """Gluon MLP through the MNISTIter facade on real handwritten digits:
    ≥97% held-out accuracy (the reference MNIST tutorial bar)."""
    mx.random.seed(42)
    train_iter = MNISTIter(image=digits_idx["train_images"],
                           label=digits_idx["train_labels"],
                           batch_size=64, flat=True, shuffle=True)
    # batch_size divides the 360-sample test split exactly: NDArrayIter's
    # pad mode would otherwise duplicate samples into the tail batch
    test_iter = MNISTIter(image=digits_idx["test_images"],
                          label=digits_idx["test_labels"],
                          batch_size=120, flat=True)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(256, activation="relu"),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _epoch in range(40):
        train_iter.reset()
        for batch in train_iter:
            data, label = batch.data[0], batch.label[0]
            with autograd.record():
                loss = loss_fn(net(data), label).mean()
            loss.backward()
            trainer.step(data.shape[0])

    correct = total = 0
    test_iter.reset()
    for batch in test_iter:
        out = net(batch.data[0])
        lab = batch.label[0].asnumpy().astype(onp.int64)
        pred = out.asnumpy().argmax(1)
        correct += int((pred[:len(lab)] == lab).sum())
        total += len(lab)
    acc = correct / total
    assert acc >= 0.97, f"MLP test accuracy {acc:.4f} < 0.97"


def _convnet_arch():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    return net


@pytest.fixture(scope="module")
def trained_convnet(digits_idx):
    """Small conv net trained through the FILE path — idx files on disk →
    MNISTIter (the reference's `src/io/iter_mnist.cc` role) → train — so
    the quantized-conv accuracy pin covers the same file→train→int8
    discipline as the reference's quantization table (VERDICT r3 weak #9)."""
    mx.random.seed(7)
    d = load_digits()
    images = (d.images * (255.0 / 16.0)).astype(onp.uint8)[..., None]
    labels = d.target.astype(onp.int32)
    rng = onp.random.RandomState(0)   # digits_idx's split/permutation
    perm = rng.permutation(len(images))
    images, labels = images[perm], labels[perm]
    n_tr = int(0.8 * len(images))

    class _IterLoader:
        """MNISTIter-backed batch source with the same normalize as the
        transforms path ((x/255 - 0.13) / 0.3); flat=False yields (N,1,8,8)."""

        def __init__(self):
            self._it = MNISTIter(image=digits_idx["train_images"],
                                 label=digits_idx["train_labels"],
                                 batch_size=64, shuffle=True, flat=False,
                                 seed=3)

        def __iter__(self):
            self._it.reset()
            for batch in self._it:
                # MNISTIter already scales to [0, 1]
                x = (batch.data[0] - 0.13) / 0.3
                yield x, batch.label[0]

    loader = _IterLoader()

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _epoch in range(12):
        for data, label in loader:
            with autograd.record():
                loss = loss_fn(net(data), label).mean()
            loss.backward()
            trainer.step(data.shape[0])

    def prep(split):
        raw = images[:n_tr] if split == "train" else images[n_tr:]
        x = (raw.astype(onp.float32) / 255.0 - 0.13) / 0.3
        return x.transpose(0, 3, 1, 2)

    x_test = prep("test")
    y_test = labels[n_tr:]
    x_train = prep("train")
    return net, x_train, x_test, y_test


def test_convnet_converges_through_dataloader(trained_convnet):
    net, _x_train, x_test, y_test = trained_convnet
    acc = _accuracy(net, x_test, y_test)
    assert acc >= 0.97, f"convnet test accuracy {acc:.4f} < 0.97"


def test_int8_accuracy_drop_within_half_percent(trained_convnet, tmp_path):
    """fp32→int8 on the TRAINED net: ≤0.5% absolute accuracy drop
    (reference: example/quantization/README.md table discipline).
    Quantizes a weight-identical COPY — quantize_net rewrites in place and
    the module-scoped fixture net is shared with the round-trip test."""
    net, x_train, x_test, y_test = trained_convnet
    acc_fp32 = _accuracy(net, x_test, y_test)
    f = str(tmp_path / "fp32.params")
    net.save_parameters(f)
    qnet = _convnet_arch()
    qnet.load_parameters(f)
    calib = [np.array(x_train[i:i + 64]) for i in range(0, 320, 64)]
    q.quantize_net(qnet, calib_data=calib, calib_mode="entropy",
                   num_calib_batches=5)
    acc_int8 = _accuracy(qnet, x_test, y_test)
    assert acc_fp32 - acc_int8 <= 0.005, (acc_fp32, acc_int8)


def test_pretrained_roundtrip_through_model_store(trained_convnet, tmp_path):
    """export_to_store → get_model_file → load_parameters round-trip, and
    the model_zoo `get_model(..., pretrained=True)` path against a store
    root holding locally-registered zoo weights."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision as zoo
    from incubator_mxnet_tpu.gluon.model_zoo.model_store import (
        export_to_store, get_model_file)

    net, _x_train, x_test, y_test = trained_convnet
    root = str(tmp_path / "store")
    fname = str(tmp_path / "digits_cnn.params")
    net.save_parameters(fname)
    del fname
    export_to_store(net, "digits_cnn", root=root)
    located = get_model_file("digits_cnn", root=root)
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
             gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
             gluon.nn.MaxPool2D(2),
             gluon.nn.Flatten(),
             gluon.nn.Dense(64, activation="relu"),
             gluon.nn.Dense(10))
    net2.load_parameters(located)
    assert _accuracy(net2, x_test, y_test) == _accuracy(net, x_test, y_test)

    # zoo path: register real (untrained-but-saved) weights for a zoo name
    # and load them back through get_model(pretrained=True)
    mlp = zoo.get_model("mobilenetv2_0.25", pretrained=False)
    mlp.initialize()
    mlp(np.array(onp.zeros((1, 3, 32, 32), "float32")))
    export_to_store(mlp, "mobilenetv2_0.25", root=root)
    loaded = zoo.get_model("mobilenetv2_0.25", pretrained=True, root=root)
    ref_param = list(mlp.collect_params().values())[0].data().asnumpy()
    got_param = list(loaded.collect_params().values())[0].data().asnumpy()
    onp.testing.assert_array_equal(ref_param, got_param)
