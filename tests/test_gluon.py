"""Gluon tests (modeled on tests/python/unittest/test_gluon.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np, npx
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def test_parameter():
    p = gluon.Parameter(shape=(5, 4))
    p.initialize(init="xavier")
    assert p.data().shape == (5, 4)
    assert p.grad().shape == (5, 4)
    p.zero_grad()
    assert p.grad().asnumpy().sum() == 0


def test_parameter_deferred_init():
    dense = nn.Dense(8)
    dense.initialize()
    x = np.ones((2, 3))
    out = dense(x)
    assert out.shape == (2, 8)
    assert dense.weight.shape == (8, 3)


def test_dense_forward():
    dense = nn.Dense(4, in_units=3, use_bias=True)
    dense.initialize(init="ones")
    # weight all ones, bias zero
    out = dense(np.ones((2, 3)))
    assert_almost_equal(out.asnumpy(), onp.full((2, 4), 3.0))


def test_sequential_and_collect_params():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8))
    net.initialize()
    _ = net(np.ones((2, 4)))
    params = net.collect_params()
    assert len(params) == 4
    names = set(params)
    assert any("weight" in n for n in names)


def test_block_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(6, in_units=4), nn.Dense(2, in_units=6))
    net.initialize()
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(6, in_units=4), nn.Dense(2, in_units=6))
    net2.initialize()
    net2.load_parameters(fname)
    x = np.ones((1, 4))
    assert_almost_equal(net(x).asnumpy(), net2(x).asnumpy())


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = np.array(onp.random.RandomState(0).uniform(1, 2, (4, 3, 5, 5))
                 .astype("float32"))
    rm0 = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        y = bn(x)
    # training mode: running stats must move toward the batch mean
    rm1 = bn.running_mean.data().asnumpy()
    assert not onp.allclose(rm0, rm1)
    # inference mode: uses running stats, output differs from training out
    y2 = bn(x)
    assert y.shape == y2.shape


def test_batchnorm_hybridized_updates_stats():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    bn.hybridize()
    x = np.array(onp.random.RandomState(0).uniform(1, 2, (4, 3, 5, 5))
                 .astype("float32"))
    with autograd.record():
        _ = bn(x)
    rm1 = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        _ = bn(x)
    rm2 = bn.running_mean.data().asnumpy()
    assert not onp.allclose(rm1, rm2), "aux update lost under jit"


def test_conv2d():
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    conv.initialize()
    out = conv(np.ones((2, 3, 16, 16)))
    assert out.shape == (2, 8, 16, 16)
    # stride
    conv2 = nn.Conv2D(4, kernel_size=3, strides=2)
    conv2.initialize()
    out2 = conv2(np.ones((2, 3, 16, 16)))
    assert out2.shape == (2, 4, 7, 7)


def test_pooling():
    x = np.ones((2, 3, 8, 8))
    assert nn.MaxPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    out = nn.GlobalMaxPool2D()(x)
    assert out.shape == (2, 3, 1, 1)


def test_dropout_modes():
    do = nn.Dropout(0.5)
    x = np.ones((100, 100))
    y_eval = do(x)
    assert_almost_equal(y_eval.asnumpy(), x.asnumpy())  # identity in inference
    with autograd.record():
        y_train = do(x)
    frac_zero = float((y_train.asnumpy() == 0).mean())
    assert 0.3 < frac_zero < 0.7


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = np.array([[1, 2], [3, 4]], dtype="int32")
    out = emb(idx)
    assert out.shape == (2, 2, 4)
    # gradient flows into the rows used
    with autograd.record():
        loss = emb(idx).sum()
    loss.backward()
    g = emb.weight.grad().asnumpy()
    assert g[1].sum() != 0 and g[0].sum() == 0


def test_layernorm():
    ln = nn.LayerNorm(in_channels=8)
    ln.initialize()
    x = np.array(onp.random.RandomState(0).normal(3, 2, (4, 8)).astype("float32"))
    y = ln(x).asnumpy()
    assert abs(y.mean()) < 1e-5
    assert abs(y.std() - 1.0) < 1e-1


def test_hybridize_consistency():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = np.array(onp.random.RandomState(0).uniform(-1, 1, (2, 8))
                 .astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid1 = net(x).asnumpy()   # first call (warmup/eager)
    hybrid2 = net(x).asnumpy()   # compiled path
    assert_almost_equal(eager, hybrid1, rtol=1e-5, atol=1e-6)
    assert_almost_equal(eager, hybrid2, rtol=1e-5, atol=1e-6)


def test_hybridized_training_matches_eager():
    def make_net():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="tanh"), nn.Dense(2))
        return net

    X = np.array(onp.random.RandomState(0).uniform(-1, 1, (8, 4))
                 .astype("float32"))
    Y = np.array(onp.random.RandomState(1).randint(0, 2, (8,)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    results = []
    for hybridize in (False, True):
        mx.random.seed(42)
        net = make_net()
        net.initialize()
        _ = net(X)
        if hybridize:
            net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.5})
        for _ in range(5):
            with autograd.record():
                loss = loss_fn(net(X), Y)
            loss.backward()
            trainer.step(8)
        results.append(float(loss.mean().item()))
    assert abs(results[0] - results[1]) < 1e-4


def test_trainer_learns():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    X = np.array(onp.random.RandomState(0).uniform(-1, 1, (64, 2))
                 .astype("float32"))
    true_w = onp.array([[2.0, -3.0]], dtype="float32")
    Y = np.array(X.asnumpy() @ true_w.T)
    loss_fn = gluon.loss.L2Loss()
    for _ in range(100):
        with autograd.record():
            loss = loss_fn(net(X), Y)
        loss.backward()
        trainer.step(64)
    assert_almost_equal(net.weight.data().asnumpy(), true_w, rtol=1e-2,
                        atol=1e-2)


def test_losses():
    pred = np.array([[1.0, 2.0], [3.0, 4.0]])
    label = np.array([[1.5, 2.5], [2.0, 3.0]])
    l2 = gluon.loss.L2Loss()(pred, label)
    assert_almost_equal(l2.asnumpy(),
                        ((onp.array([[1, 2], [3, 4.0]])
                          - onp.array([[1.5, 2.5], [2, 3.0]])) ** 2 / 2)
                        .mean(axis=1))
    l1 = gluon.loss.L1Loss()(pred, label)
    assert l1.shape == (2,)
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    out = sce(np.array([[10.0, 0.0], [0.0, 10.0]]), np.array([0, 1]))
    assert float(out.mean().item()) < 0.01
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    out = bce(np.array([10.0, -10.0]), np.array([1.0, 0.0]))
    assert float(out.mean().item()) < 0.01
    huber = gluon.loss.HuberLoss()(pred, label)
    assert huber.shape == (2,)
    kl = gluon.loss.KLDivLoss()
    p = npx.log_softmax(np.array([[1.0, 2.0, 3.0]]))
    q = npx.softmax(np.array([[1.0, 2.0, 3.0]]))
    assert abs(float(kl(p, q).item())) < 1e-6


def test_metrics():
    acc = gluon.metric.Accuracy()
    acc.update(np.array([1, 0]), np.array([[0.1, 0.9], [0.8, 0.2]]))
    assert acc.get()[1] == 1.0
    acc.update(np.array([0]), np.array([[0.1, 0.9]]))
    assert acc.get()[1] == 2 / 3
    mae = gluon.metric.MAE()
    mae.update(np.array([1.0, 2.0]), np.array([1.5, 2.5]))
    assert abs(mae.get()[1] - 0.5) < 1e-6
    comp = gluon.metric.create(["accuracy", "mae"])
    assert isinstance(comp, gluon.metric.CompositeEvalMetric)


def test_constant_param():
    c = gluon.Constant(np.array([1.0, 2.0]))
    c.initialize()
    assert_almost_equal(c.data().asnumpy(), onp.array([1.0, 2.0]))


def test_model_export(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    net.hybridize()
    _ = net(np.ones((1, 3)))
    sym_file, param_file = net.export(str(tmp_path / "model"))
    import os

    assert os.path.exists(sym_file)
    assert os.path.exists(param_file)


def test_dataloader_shared_memory_transport():
    """Multi-worker DataLoader ships large batches through POSIX shm (the
    reference's CPUSharedStorage role): values identical to the in-process
    path, no leaked /dev/shm segments after the epoch."""
    import glob as _glob
    import numpy as onp

    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    rng = onp.random.RandomState(0)
    # 1 MB+ per batch => the shm path engages (threshold 1 MB)
    X = rng.uniform(-1, 1, (64, 64, 64)).astype("float32")
    Y = onp.arange(64, dtype=onp.int32)
    ds = ArrayDataset(X, Y)
    before = set(_glob.glob("/dev/shm/psm_*"))
    ref_loader = DataLoader(ds, batch_size=16, num_workers=0)
    shm_loader = DataLoader(ds, batch_size=16, num_workers=2)
    ref = [tuple(a.asnumpy() for a in b) for b in ref_loader]
    got = [tuple(a.asnumpy() for a in b) for b in shm_loader]
    assert len(ref) == len(got) == 4
    for (rx, ry), (gx, gy) in zip(ref, got):
        onp.testing.assert_array_equal(rx, gx)
        onp.testing.assert_array_equal(ry, gy)
    leaked = set(_glob.glob("/dev/shm/psm_*")) - before
    assert not leaked, leaked


def test_dataloader_workers_with_jax_initialized_parent():
    """Regression: worker pool must not `fork` a JAX-multithreaded parent
    (that deadlocked in round 3). Force backend threads alive first."""
    import numpy as onp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    (mx.np.ones((8, 8)) @ mx.np.ones((8, 8))).asnumpy()  # spin up backend
    X = onp.arange(32 * 4, dtype="float32").reshape(32, 4)
    loader = DataLoader(ArrayDataset(X), batch_size=8, num_workers=2,
                        timeout=60)
    got = onp.concatenate([b.asnumpy() for (b,) in
                           ((bb,) if not isinstance(bb, tuple) else bb
                            for bb in loader)])
    onp.testing.assert_array_equal(got, X)


def test_dataloader_early_close_releases_shm():
    """Abandoning the iterator with prefetched shm batches in flight must
    unlink every segment (ADVICE r3: early generator close leaked shm)."""
    import glob as _glob

    import numpy as onp

    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    X = onp.random.RandomState(1).uniform(
        -1, 1, (64, 64, 64)).astype("float32")  # 1 MB/batch => shm path
    before = set(_glob.glob("/dev/shm/psm_*"))
    loader = DataLoader(ArrayDataset(X), batch_size=16, num_workers=2,
                        prefetch=4, timeout=60)
    it = iter(loader)
    next(it)           # one batch consumed; ~3 prefetched still in flight
    it.close()         # abandon early
    del loader
    import gc
    import time

    gc.collect()
    time.sleep(0.5)
    leaked = set(_glob.glob("/dev/shm/psm_*")) - before
    assert not leaked, leaked
