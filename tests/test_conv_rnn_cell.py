"""Convolutional RNN cell tests (reference:
tests/python/unittest/test_gluon_rnn.py conv cell cases)."""
import numpy as onp

from incubator_mxnet_tpu import autograd, np
from incubator_mxnet_tpu.gluon.rnn import ConvGRUCell, ConvLSTMCell, ConvRNNCell

B, C, H, W = 2, 3, 8, 8
RNG = onp.random.RandomState(21)


def _x():
    return np.array(RNG.randn(B, C, H, W).astype("float32") * 0.1)


def test_conv_rnn_cell_shapes():
    cell = ConvRNNCell(hidden_channels=4, kernel_size=3)
    cell.initialize()
    out, states = cell(_x(), cell_begin(cell))
    assert out.shape == (B, 4, H, W)
    assert states[0].shape == (B, 4, H, W)


def cell_begin(cell):
    # first call infers spatial dims; emulate with a manual zero state
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    n_states = 2 if isinstance(cell, ConvLSTMCell) else 1
    return [NDArray(jnp.zeros((B, cell._hidden, H, W)))
            for _ in range(n_states)]


def test_conv_lstm_cell_runs_and_grads():
    cell = ConvLSTMCell(hidden_channels=4, kernel_size=3)
    cell.initialize()
    x = _x()
    states = cell_begin(cell)
    with autograd.record():
        out1, states = cell(x, states)
        out2, states = cell(x, states)
        loss = (out2 * out2).sum()
    loss.backward()
    g = cell.i2h_weight.data()._grad
    assert g is not None and onp.isfinite(g.asnumpy()).all()
    assert onp.abs(g.asnumpy()).sum() > 0
    # begin_state works after spatial dims are known
    st = cell.begin_state(B)
    assert st[0].shape == (B, 4, H, W) and len(st) == 2


def test_conv_gru_cell_state_update():
    cell = ConvGRUCell(hidden_channels=2, kernel_size=3)
    cell.initialize()
    x = _x()
    out, [h] = cell(x, cell_begin(cell))
    assert out.shape == (B, 2, H, W)
    out2, [h2] = cell(x, [h])
    assert onp.abs(h2.asnumpy() - h.asnumpy()).sum() > 0


def test_conv_cell_unroll():
    cell = ConvLSTMCell(hidden_channels=2, kernel_size=3)
    cell.initialize()
    seq = np.array(RNG.randn(B, 4, C, H, W).astype("float32") * 0.1)
    cell(seq[:, 0], cell_begin(cell))  # infer shapes
    outs, states = cell.unroll(4, seq, layout="NTC")
    assert outs.shape == (B, 4, 2, H, W)


def test_conv_cell_input_shape_begin_state():
    cell = ConvLSTMCell(hidden_channels=4, kernel_size=3,
                        input_shape=(C, H, W))
    cell.initialize()
    st = cell.begin_state(B)  # no forward needed when input_shape given
    assert st[0].shape == (B, 4, H, W) and len(st) == 2
    out, st = cell(_x(), st)
    assert out.shape == (B, 4, H, W)
