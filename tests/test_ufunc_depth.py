"""Per-ufunc depth: every `mx.np` elementwise op checked against the
NumPy golden on float32, on bfloat16 (loose tol — verifies the op ACCEPTS
and preserves bf16, the TPU compute dtype), and through autograd where
differentiable (reference: the per-op functions of
`tests/python/unittest/test_numpy_op.py`, the largest reference suite)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, np

RNG = onp.random.RandomState(7)


def _u(lo, hi, shape=(3, 4)):
    return RNG.uniform(lo, hi, shape).astype("float32")


def check_unary(name, ref, lo=-2.0, hi=2.0, grad=True, bf16=True,
                shape=(3, 4), rtol=1e-5, atol=1e-6):
    fn = getattr(np, name)
    xv = _u(lo, hi, shape)
    x = np.array(xv)
    got = fn(x)
    onp.testing.assert_allclose(got.asnumpy(), ref(xv.astype("float64")),
                                rtol=rtol, atol=atol)
    if bf16:
        xb = np.array(xv).astype("bfloat16")
        gb = fn(xb)
        assert "bfloat16" in str(gb.dtype), (name, gb.dtype)
        onp.testing.assert_allclose(
            gb.astype("float32").asnumpy(), ref(xv.astype("float64")),
            rtol=0.05, atol=0.05)
    if grad:
        xg = np.array(xv)
        xg.attach_grad()
        with autograd.record():
            y = fn(xg)
        y.backward()
        eps = 1e-3
        num = (ref(xv.astype("float64") + eps)
               - ref(xv.astype("float64") - eps)) / (2 * eps)
        onp.testing.assert_allclose(xg.grad.asnumpy(), num, rtol=2e-2,
                                    atol=2e-3)


def check_binary(name, ref, lo=-2.0, hi=2.0, lo2=None, hi2=None,
                 rtol=1e-5, atol=1e-6):
    fn = getattr(np, name)
    av = _u(lo, hi)
    bv = _u(lo if lo2 is None else lo2, hi if hi2 is None else hi2)
    got = fn(np.array(av), np.array(bv))
    onp.testing.assert_allclose(
        got.asnumpy(), ref(av.astype("float64"), bv.astype("float64")),
        rtol=rtol, atol=atol)
    # broadcasting: row vector against the matrix
    got2 = fn(np.array(av), np.array(bv[:1]))
    onp.testing.assert_allclose(
        got2.asnumpy(), ref(av.astype("float64"),
                            bv[:1].astype("float64")),
        rtol=rtol, atol=atol)


# -- unary: algebraic --------------------------------------------------------

def test_negative():
    check_unary("negative", lambda x: -x)


def test_abs():
    check_unary("abs", onp.abs, grad=False)


def test_absolute():
    check_unary("absolute", onp.abs, grad=False)


def test_sign():
    check_unary("sign", onp.sign, grad=False)


def test_square():
    check_unary("square", onp.square)


def test_sqrt():
    check_unary("sqrt", onp.sqrt, lo=0.05, hi=4.0)


def test_cbrt():
    check_unary("cbrt", onp.cbrt, lo=0.05, hi=4.0)


def test_reciprocal():
    check_unary("reciprocal", onp.reciprocal, lo=0.2, hi=3.0)


# -- unary: exponential / log ------------------------------------------------

def test_exp():
    check_unary("exp", onp.exp)


def test_expm1():
    check_unary("expm1", onp.expm1)


def test_exp2():
    check_unary("exp2", onp.exp2)


def test_log():
    check_unary("log", onp.log, lo=0.05, hi=5.0)


def test_log2():
    check_unary("log2", onp.log2, lo=0.05, hi=5.0)


def test_log10():
    check_unary("log10", onp.log10, lo=0.05, hi=5.0)


def test_log1p():
    check_unary("log1p", onp.log1p, lo=-0.5, hi=5.0)


# -- unary: trig -------------------------------------------------------------

def test_sin():
    check_unary("sin", onp.sin)


def test_cos():
    check_unary("cos", onp.cos)


def test_tan():
    check_unary("tan", onp.tan, lo=-1.0, hi=1.0)


def test_arcsin():
    check_unary("arcsin", onp.arcsin, lo=-0.9, hi=0.9)


def test_arccos():
    check_unary("arccos", onp.arccos, lo=-0.9, hi=0.9)


def test_arctan():
    check_unary("arctan", onp.arctan)


def test_degrees():
    check_unary("degrees", onp.degrees)


def test_radians():
    check_unary("radians", onp.radians)


# -- unary: hyperbolic -------------------------------------------------------

def test_sinh():
    check_unary("sinh", onp.sinh)


def test_cosh():
    check_unary("cosh", onp.cosh)


def test_tanh():
    check_unary("tanh", onp.tanh)


def test_arcsinh():
    check_unary("arcsinh", onp.arcsinh)


def test_arccosh():
    check_unary("arccosh", onp.arccosh, lo=1.1, hi=4.0)


def test_arctanh():
    check_unary("arctanh", onp.arctanh, lo=-0.9, hi=0.9)


# -- unary: rounding (not differentiable) ------------------------------------

def test_floor():
    check_unary("floor", onp.floor, grad=False)


def test_ceil():
    check_unary("ceil", onp.ceil, grad=False)


def test_trunc():
    check_unary("trunc", onp.trunc, grad=False)


def test_rint():
    check_unary("rint", onp.rint, grad=False)


def test_round():
    check_unary("round", onp.round, grad=False)


def test_fix():
    check_unary("fix", onp.fix, grad=False)


# -- binary arithmetic -------------------------------------------------------

def test_add():
    check_binary("add", onp.add)


def test_subtract():
    check_binary("subtract", onp.subtract)


def test_multiply():
    check_binary("multiply", onp.multiply)


def test_divide():
    check_binary("divide", onp.divide, lo2=0.2, hi2=3.0)


def test_true_divide():
    check_binary("true_divide", onp.true_divide, lo2=0.2, hi2=3.0)


def test_floor_divide():
    check_binary("floor_divide", onp.floor_divide, lo2=0.2, hi2=3.0)


def test_mod():
    check_binary("mod", onp.mod, lo2=0.2, hi2=3.0)


def test_remainder():
    check_binary("remainder", onp.remainder, lo2=0.2, hi2=3.0)


def test_power():
    check_binary("power", onp.power, lo=0.2, hi=2.0)


def test_maximum():
    check_binary("maximum", onp.maximum)


def test_minimum():
    check_binary("minimum", onp.minimum)


def test_hypot():
    check_binary("hypot", onp.hypot)


def test_arctan2():
    check_binary("arctan2", onp.arctan2)


def test_fmod():
    check_binary("fmod", onp.fmod, lo2=0.2, hi2=3.0)


def test_copysign():
    check_binary("copysign", onp.copysign)


def test_logaddexp():
    check_binary("logaddexp", onp.logaddexp)


# -- comparisons -------------------------------------------------------------

def test_equal():
    check_binary("equal", onp.equal, rtol=0, atol=0)


def test_not_equal():
    check_binary("not_equal", onp.not_equal, rtol=0, atol=0)


def test_greater():
    check_binary("greater", onp.greater, rtol=0, atol=0)


def test_greater_equal():
    check_binary("greater_equal", onp.greater_equal, rtol=0, atol=0)


def test_less():
    check_binary("less", onp.less, rtol=0, atol=0)


def test_less_equal():
    check_binary("less_equal", onp.less_equal, rtol=0, atol=0)


# -- logical -----------------------------------------------------------------

def test_logical_and():
    a = onp.array([[0.0, 1.0], [2.0, 0.0]], "float32")
    b = onp.array([[1.0, 0.0], [3.0, 0.0]], "float32")
    got = np.logical_and(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_array_equal(got, onp.logical_and(a, b))


def test_logical_or():
    a = onp.array([[0.0, 1.0], [2.0, 0.0]], "float32")
    b = onp.array([[1.0, 0.0], [3.0, 0.0]], "float32")
    got = np.logical_or(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_array_equal(got, onp.logical_or(a, b))


def test_logical_xor():
    a = onp.array([[0.0, 1.0], [2.0, 0.0]], "float32")
    b = onp.array([[1.0, 0.0], [3.0, 0.0]], "float32")
    got = np.logical_xor(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_array_equal(got, onp.logical_xor(a, b))


def test_logical_not():
    a = onp.array([[0.0, 1.0], [2.0, 0.0]], "float32")
    got = np.logical_not(np.array(a)).asnumpy()
    onp.testing.assert_array_equal(got, onp.logical_not(a))


# -- float inspection --------------------------------------------------------

def test_isnan():
    a = onp.array([1.0, onp.nan, onp.inf], "float32")
    onp.testing.assert_array_equal(np.isnan(np.array(a)).asnumpy(),
                                   onp.isnan(a))


def test_isinf():
    a = onp.array([1.0, onp.nan, onp.inf, -onp.inf], "float32")
    onp.testing.assert_array_equal(np.isinf(np.array(a)).asnumpy(),
                                   onp.isinf(a))


def test_isfinite():
    a = onp.array([1.0, onp.nan, onp.inf], "float32")
    onp.testing.assert_array_equal(np.isfinite(np.array(a)).asnumpy(),
                                   onp.isfinite(a))


def test_isposinf():
    a = onp.array([1.0, onp.inf, -onp.inf], "float32")
    onp.testing.assert_array_equal(np.isposinf(np.array(a)).asnumpy(),
                                   onp.isposinf(a))


def test_isneginf():
    a = onp.array([1.0, onp.inf, -onp.inf], "float32")
    onp.testing.assert_array_equal(np.isneginf(np.array(a)).asnumpy(),
                                   onp.isneginf(a))


# -- scalar mixing / dtype promotion -----------------------------------------

def test_scalar_add_keeps_dtype():
    x = np.array(onp.ones((2, 2), "float32"))
    assert str((x + 1).dtype) == "float32"
    xb = x.astype("bfloat16")
    assert "bfloat16" in str((xb + 1).dtype)


def test_scalar_radd_rsub_rmul():
    xv = _u(-2, 2)
    x = np.array(xv)
    onp.testing.assert_allclose((1.0 + x).asnumpy(), 1.0 + xv, rtol=1e-6)
    onp.testing.assert_allclose((1.0 - x).asnumpy(), 1.0 - xv, rtol=1e-6)
    onp.testing.assert_allclose((2.0 * x).asnumpy(), 2.0 * xv, rtol=1e-6)


def test_scalar_rdiv_rpow():
    xv = _u(0.5, 2.0)
    x = np.array(xv)
    onp.testing.assert_allclose((1.0 / x).asnumpy(), 1.0 / xv, rtol=1e-6)
    onp.testing.assert_allclose((2.0 ** x).asnumpy(), 2.0 ** xv, rtol=1e-5)


def test_int_float_promotion():
    a = np.array(onp.arange(4, dtype="int32"))
    b = np.array(onp.ones(4, "float32"))
    assert "float" in str((a + b).dtype)


def test_bf16_f32_promotion():
    a = np.array(onp.ones((2, 2), "float32")).astype("bfloat16")
    b = np.array(onp.ones((2, 2), "float32"))
    out = a + b
    assert str(out.dtype) == "float32"


# -- binary grads ------------------------------------------------------------

def _binary_grad(name, ref_da, ref_db, lo=0.5, hi=2.0):
    fn = getattr(np, name)
    av, bv = _u(lo, hi), _u(lo, hi)
    a, b = np.array(av), np.array(bv)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = fn(a, b)
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), ref_da(av, bv),
                                rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(b.grad.asnumpy(), ref_db(av, bv),
                                rtol=1e-4, atol=1e-5)


def test_add_grad():
    _binary_grad("add", lambda a, b: onp.ones_like(a),
                 lambda a, b: onp.ones_like(b))


def test_subtract_grad():
    _binary_grad("subtract", lambda a, b: onp.ones_like(a),
                 lambda a, b: -onp.ones_like(b))


def test_multiply_grad():
    _binary_grad("multiply", lambda a, b: b, lambda a, b: a)


def test_divide_grad():
    _binary_grad("divide", lambda a, b: 1.0 / b, lambda a, b: -a / b ** 2)


def test_power_grad():
    _binary_grad("power", lambda a, b: b * a ** (b - 1),
                 lambda a, b: a ** b * onp.log(a))


def test_maximum_grad_routes_to_winner():
    av = onp.array([[1.0, 5.0]], "float32")
    bv = onp.array([[3.0, 2.0]], "float32")
    a, b = np.array(av), np.array(bv)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        y = np.maximum(a, b)
    y.backward()
    onp.testing.assert_array_equal(a.grad.asnumpy(), [[0.0, 1.0]])
    onp.testing.assert_array_equal(b.grad.asnumpy(), [[1.0, 0.0]])


# -- special values ----------------------------------------------------------

def test_log_of_zero_is_neg_inf():
    out = np.log(np.array(onp.zeros(2, "float32"))).asnumpy()
    assert onp.all(onp.isneginf(out))


def test_sqrt_of_negative_is_nan():
    out = np.sqrt(np.array(onp.full(2, -1.0, "float32"))).asnumpy()
    assert onp.all(onp.isnan(out))


def test_divide_by_zero_is_inf():
    out = np.divide(np.array(onp.ones(2, "float32")),
                    np.array(onp.zeros(2, "float32"))).asnumpy()
    assert onp.all(onp.isinf(out))


def test_zero_over_zero_is_nan():
    out = np.divide(np.array(onp.zeros(2, "float32")),
                    np.array(onp.zeros(2, "float32"))).asnumpy()
    assert onp.all(onp.isnan(out))


def test_exp_overflow_to_inf():
    out = np.exp(np.array(onp.full(2, 1e4, "float32"))).asnumpy()
    assert onp.all(onp.isinf(out))


def test_expit_like_sigmoid_saturates():
    from incubator_mxnet_tpu import npx

    out = npx.sigmoid(np.array(onp.array([-100.0, 100.0], "float32")))
    onp.testing.assert_allclose(out.asnumpy(), [0.0, 1.0], atol=1e-6)


# -- clip / interp-style -----------------------------------------------------

def test_clip():
    xv = _u(-3, 3)
    got = np.clip(np.array(xv), -1.0, 1.0).asnumpy()
    onp.testing.assert_allclose(got, onp.clip(xv, -1.0, 1.0))


def test_clip_grad_zero_outside():
    xv = onp.array([-2.0, 0.5, 2.0], "float32")
    x = np.array(xv)
    x.attach_grad()
    with autograd.record():
        y = np.clip(x, -1.0, 1.0)
    y.backward()
    onp.testing.assert_array_equal(x.grad.asnumpy(), [0.0, 1.0, 0.0])


def test_fabs():
    check_unary("fabs", onp.fabs, grad=False)


def test_heaviside():
    a = onp.array([-1.0, 0.0, 2.0], "float32")
    got = np.heaviside(np.array(a), np.array(
        onp.full(3, 0.5, "float32"))).asnumpy()
    onp.testing.assert_allclose(got, onp.heaviside(a, 0.5))


def test_nan_to_num():
    a = onp.array([onp.nan, onp.inf, -onp.inf, 1.0], "float32")
    got = np.nan_to_num(np.array(a)).asnumpy()
    onp.testing.assert_allclose(got, onp.nan_to_num(a))