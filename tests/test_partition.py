"""Partition/subgraph backend API (`incubator_mxnet_tpu/partition.py`;
reference: `src/operator/subgraph/subgraph_property.h:88,265,543` +
`HybridBlock.optimize_for`). Covers: the op-level jaxpr outlining, chain
matching + splicing, the built-in flash-attention and int8 backends, and
a custom out-of-tree backend swapping a matched subgraph."""
import math

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np, npx, partition
from incubator_mxnet_tpu.partition import (Backend, Pattern, get_backend,
                                           register_backend, rewrite_jaxpr)


class _Attn(gluon.HybridBlock):
    """Unfused attention written with framework ops — the match target."""

    def __init__(self, d, scale=True):
        super().__init__()
        self._d = d
        self._scale = scale

    def forward(self, q, k, v):
        s = npx.batch_dot(q, k, transpose_b=True)
        if self._scale:
            s = s / math.sqrt(self._d)
        p = npx.softmax(s, axis=-1)
        return npx.batch_dot(p, v)


def _qkv(B=4, T=32, D=16, seed=0):
    rng = onp.random.RandomState(seed)
    return tuple(np.array(rng.randn(B, T, D).astype("float32"))
                 for _ in range(3))


def test_builtin_backends_registered():
    assert "flash_attention" in partition.list_backends()
    assert "int8" in partition.list_backends()
    with pytest.raises(ValueError):
        get_backend("no_such_backend")


@pytest.mark.parametrize("scale", [True, False])
def test_flash_attention_rewrite_matches_unfused(scale):
    q, k, v = _qkv()
    net = _Attn(16, scale=scale)
    ref = net(q, k, v).asnumpy()
    b = get_backend("flash_attention")
    b.last_rewrites = 0
    out = net.optimize_for(q, k, v, backend="flash_attention").asnumpy()
    assert b.last_rewrites == 1
    onp.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)
    # the compiled path replays on later calls
    out2 = net(q, k, v).asnumpy()
    onp.testing.assert_allclose(out2, out, rtol=1e-6, atol=1e-6)


def test_flash_rewrite_keeps_gradients():
    """The spliced kernel must be differentiable through autograd."""
    from incubator_mxnet_tpu import autograd

    q, k, v = _qkv(seed=3)
    net = _Attn(16)
    for a in (q, k, v):
        a.attach_grad()
    with autograd.record():
        ref = net(q, k, v)
    ref.backward()
    g_ref = q.grad.asnumpy().copy()

    net2 = _Attn(16)
    net2.optimize_for(q, k, v, backend="flash_attention")
    for a in (q, k, v):
        a.attach_grad()   # reset grads
    with autograd.record():
        out = net2(q, k, v)
    out.backward()
    onp.testing.assert_allclose(q.grad.asnumpy(), g_ref,
                                rtol=2e-4, atol=2e-5)


def test_guard_rejects_nonstandard_layout():
    """transpose_b=False attention (k already transposed) must NOT fuse —
    the guard can't identify the layout, so the graph stays unfused but
    CORRECT."""
    class OddAttn(gluon.HybridBlock):
        def forward(self, q, kt, v):
            s = npx.batch_dot(q, kt)          # k pre-transposed
            p = npx.softmax(s, axis=-1)
            return npx.batch_dot(p, v)

    rng = onp.random.RandomState(1)
    q = np.array(rng.randn(4, 32, 16).astype("float32"))
    kt = np.array(rng.randn(4, 16, 32).astype("float32"))
    v = np.array(rng.randn(4, 32, 16).astype("float32"))
    net = OddAttn()
    ref = net(q, kt, v).asnumpy()
    b = get_backend("flash_attention")
    b.last_rewrites = -1
    out = net.optimize_for(q, kt, v, backend="flash_attention").asnumpy()
    assert b.last_rewrites == 0
    onp.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_guard_rejects_square_k_without_transpose():
    """batch_dot(q, k, transpose_b=False) with SQUARE k (Tk==head_dim,
    T!=d) is shape-indistinguishable from q@k^T; the transpose flags in
    the outlined eqn's static_info must reject the rewrite (r3 ADVICE:
    this case silently corrupted results)."""
    class SquareK(gluon.HybridBlock):
        def forward(self, q, k, v):
            s = npx.batch_dot(q, k)           # NO transpose; k is (B,d,d)
            p = npx.softmax(s, axis=-1)
            return npx.batch_dot(p, v)

    rng = onp.random.RandomState(3)
    B, T, d = 2, 24, 16                        # T != d, k square (d,d)
    q = np.array(rng.randn(B, T, d).astype("float32"))
    k = np.array(rng.randn(B, d, d).astype("float32"))
    v = np.array(rng.randn(B, d, d).astype("float32"))
    net = SquareK()
    ref = net(q, k, v).asnumpy()
    b = get_backend("flash_attention")
    b.last_rewrites = -1
    out = net.optimize_for(q, k, v, backend="flash_attention").asnumpy()
    assert b.last_rewrites == 0
    onp.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_guard_rejects_transposed_pv_stage():
    """att @ v^T must not fuse: the pallas kernel computes att @ v."""
    class OddPV(gluon.HybridBlock):
        def forward(self, q, k, v):
            s = npx.batch_dot(q, k, transpose_b=True)
            p = npx.softmax(s, axis=-1)
            return npx.batch_dot(p, v, transpose_b=True)

    rng = onp.random.RandomState(4)
    q = np.array(rng.randn(2, 32, 32).astype("float32"))
    k = np.array(rng.randn(2, 32, 32).astype("float32"))
    v = np.array(rng.randn(2, 32, 32).astype("float32"))
    net = OddPV()
    ref = net(q, k, v).asnumpy()
    b = get_backend("flash_attention")
    b.last_rewrites = -1
    out = net.optimize_for(q, k, v, backend="flash_attention").asnumpy()
    assert b.last_rewrites == 0
    onp.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_int8_backend_block_rewrite():
    """optimize_for(backend='int8') routes through quantize_net."""
    from incubator_mxnet_tpu.contrib import quantization as q

    rng = onp.random.RandomState(2)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    x = np.array(rng.uniform(-1, 1, (8, 8)).astype("float32"))
    ref = net(x).asnumpy()
    out = net.optimize_for(
        x, backend="int8",
        backend_opts={"calib_data": [x], "calib_mode": "naive"}).asnumpy()
    assert type(net._children["0"]) is q.QuantizedDense
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.05


def test_custom_backend_swaps_matched_subgraph():
    """An out-of-tree backend: outline `gelu`, replace exact-erf gelu with
    the tanh approximation — the VERDICT's 'custom hook swapping a matched
    subgraph' acceptance case."""
    import jax.numpy as jnp

    def tanh_gelu(eqns, invals):   # noqa: ARG001
        (x,) = invals
        c = math.sqrt(2.0 / math.pi)
        return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x ** 3)))

    class TanhGeluBackend(Backend):
        name = "tanh_gelu_test"
        mark_ops = frozenset({"gelu"})
        patterns = [Pattern("gelu", ["gelu"], tanh_gelu)]

    register_backend(TanhGeluBackend)

    class Net(gluon.HybridBlock):
        def forward(self, x):
            return npx.gelu(x) * 2.0

    rng = onp.random.RandomState(4)
    x = np.array(rng.randn(8, 64).astype("float32"))
    net = Net()
    ref = net(x).asnumpy()
    b = get_backend("tanh_gelu_test")
    b.last_rewrites = 0
    out = net.optimize_for(x, backend="tanh_gelu_test").asnumpy()
    assert b.last_rewrites == 1
    # tanh-approx differs from erf-exact but only slightly
    assert not onp.array_equal(out, ref)
    onp.testing.assert_allclose(out, ref, rtol=5e-3, atol=5e-3)


def test_rewrite_jaxpr_direct():
    """Matcher unit test on a hand-built jaxpr: single-consumer discipline
    (no fuse when an intermediate feeds two consumers)."""
    import jax

    from incubator_mxnet_tpu.partition import backend_scope

    b = get_backend("flash_attention")
    rng = onp.random.RandomState(0)
    qv = onp.random.randn(2, 8, 4).astype("float32")

    def two_consumer(q, k, v):
        from incubator_mxnet_tpu.ndarray.ndarray import NDArray

        s = npx.batch_dot(NDArray(q), NDArray(k), transpose_b=True)
        p = npx.softmax(s, axis=-1)
        o = npx.batch_dot(p, NDArray(v))
        # second consumer of the softmax output => chain must NOT fuse
        return (o + p.sum())._data

    with backend_scope(b):
        closed = jax.make_jaxpr(two_consumer)(qv, qv, qv)
    _, n = rewrite_jaxpr(closed, b.patterns)
    assert n == 0
    del rng


def test_chain_input_produced_between_matched_eqns():
    """v traced AFTER the softmax (interleaved producer): the splice must
    land after v's producer or eval_jaxpr hits use-before-def."""
    class LateV(gluon.HybridBlock):
        def forward(self, q, k, x):
            s = npx.batch_dot(q, k, transpose_b=True)
            p = npx.softmax(s / 4.0, axis=-1)
            v = x * 2.0 + 1.0            # produced between match and use
            return npx.batch_dot(p, v)

    rng = onp.random.RandomState(6)
    q = np.array(rng.randn(2, 16, 8).astype("float32"))
    k = np.array(rng.randn(2, 16, 8).astype("float32"))
    x = np.array(rng.randn(2, 16, 8).astype("float32"))
    net = LateV()
    ref = net(q, k, x).asnumpy()
    b = get_backend("flash_attention")
    b.last_rewrites = 0
    out = net.optimize_for(q, k, x, backend="flash_attention").asnumpy()
    assert b.last_rewrites == 1
    onp.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_guard_rejects_wrong_softmax_axis():
    """softmax over a non-last axis must NOT fuse — the kernel softmaxes
    the last axis; the outliner carries the op's axis in the eqn name."""
    class WrongAxis(gluon.HybridBlock):
        def forward(self, q, k, v):
            s = npx.batch_dot(q, k, transpose_b=True)
            p = npx.softmax(s, axis=1)       # wrong axis on purpose
            return npx.batch_dot(p, v)

    rng = onp.random.RandomState(9)
    q = np.array(rng.randn(2, 12, 8).astype("float32"))
    k = np.array(rng.randn(2, 12, 8).astype("float32"))
    v = np.array(rng.randn(2, 12, 8).astype("float32"))
    net = WrongAxis()
    ref = net(q, k, v).asnumpy()
    b = get_backend("flash_attention")
    b.last_rewrites = -1
    out = net.optimize_for(q, k, v, backend="flash_attention").asnumpy()
    assert b.last_rewrites == 0
    onp.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
