"""CSVIter / LibSVMIter tests (reference: tests/python/unittest/test_io.py)."""
import numpy as onp

from incubator_mxnet_tpu.io import CSVIter, LibSVMIter


def test_csv_iter(tmp_path):
    data = onp.arange(20, dtype=onp.float32).reshape(10, 2)
    labels = onp.arange(10, dtype=onp.float32)
    dpath, lpath = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    onp.savetxt(dpath, data, delimiter=",")
    onp.savetxt(lpath, labels, delimiter=",")
    it = CSVIter(data_csv=dpath, data_shape=(2,), label_csv=lpath,
                 label_shape=(1,), batch_size=4)
    batches = list(it)
    assert len(batches) == 3  # 10 rows, pad to 12
    onp.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    onp.testing.assert_allclose(batches[0].label[0].asnumpy().ravel(),
                                labels[:4])
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_csv_iter_no_label(tmp_path):
    data = onp.ones((4, 3), onp.float32)
    dpath = str(tmp_path / "d.csv")
    onp.savetxt(dpath, data, delimiter=",")
    it = CSVIter(data_csv=dpath, data_shape=(3,), batch_size=2)
    b = next(it)
    assert b.data[0].shape == (2, 3)


def test_libsvm_iter(tmp_path):
    path = str(tmp_path / "d.svm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:0.5\n")
        f.write("1 2:1.0 3:3.0\n")
    it = LibSVMIter(data_libsvm=path, data_shape=(4,), batch_size=2)
    b = next(it)
    onp.testing.assert_allclose(b.data[0].asnumpy(),
                                [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    onp.testing.assert_allclose(b.label[0].asnumpy().ravel(), [1, 0])
    # sparse view on demand
    assert it.to_csr().shape == (3, 4)


def test_libsvm_iter_separate_label_file(tmp_path):
    dpath = str(tmp_path / "d.svm")
    lpath = str(tmp_path / "l.svm")
    with open(dpath, "w") as f:
        f.write("0 0:1.0\n0 1:2.0\n")
    with open(lpath, "w") as f:
        f.write("5\n7\n")
    it = LibSVMIter(data_libsvm=dpath, data_shape=(2,),
                    label_libsvm=lpath, batch_size=2)
    b = next(it)
    onp.testing.assert_allclose(b.label[0].asnumpy().ravel(), [5, 7])
