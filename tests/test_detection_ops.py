"""RPN / RoI detection op family (reference `src/operator/contrib/
proposal.cc`, `psroi_pooling.cc`, `deformable_psroi_pooling.cc`,
`rroi_align.cc`, `mrcnn_mask_target.cu`)."""
import numpy as onp

from incubator_mxnet_tpu import np, npx


def _r(*shape, seed=0, lo=-1.0, hi=1.0):
    return np.array(onp.random.RandomState(seed)
                    .uniform(lo, hi, shape).astype("float32"))


def test_proposal_shapes_and_validity():
    a = 3 * 4          # ratios x scales... 3 ratios x 4 scales = 12
    h = w = 8
    cls = _r(1, 2 * a, h, w, lo=0, hi=1)
    bbox = _r(1, 4 * a, h, w, seed=1, lo=-0.2, hi=0.2)
    im_info = np.array(onp.array([[128.0, 128.0, 1.0]], "float32"))
    rois = npx.proposal(cls, bbox, im_info, rpn_pre_nms_top_n=200,
                        rpn_post_nms_top_n=20, feature_stride=16)
    assert rois.shape == (20, 5)
    rn = rois.asnumpy()
    assert (rn[:, 0] == 0).all()                   # batch index
    assert (rn[:, 1] >= 0).all() and (rn[:, 3] <= 127.0).all()
    assert (rn[:, 3] >= rn[:, 1]).all() and (rn[:, 4] >= rn[:, 2]).all()


def test_proposal_output_score():
    a = 12
    cls = _r(1, 2 * a, 4, 4, lo=0, hi=1)
    bbox = _r(1, 4 * a, 4, 4, seed=1, lo=-0.1, hi=0.1)
    im_info = np.array(onp.array([[64.0, 64.0, 1.0]], "float32"))
    rois, scores = npx.proposal(cls, bbox, im_info,
                                rpn_pre_nms_top_n=50,
                                rpn_post_nms_top_n=10,
                                output_score=True)
    assert rois.shape == (10, 5) and scores.shape == (10, 1)
    sn = scores.asnumpy().ravel()
    assert (onp.diff(sn[sn > 0]) <= 1e-6).all()    # sorted descending


def test_multi_proposal_batch_indices():
    a = 12
    cls = _r(2, 2 * a, 4, 4, lo=0, hi=1)
    bbox = _r(2, 4 * a, 4, 4, seed=1, lo=-0.1, hi=0.1)
    im_info = np.array(onp.array([[64.0, 64.0, 1.0]] * 2, "float32"))
    rois = npx.multi_proposal(cls, bbox, im_info,
                              rpn_pre_nms_top_n=50,
                              rpn_post_nms_top_n=8)
    assert rois.shape == (16, 5)
    rn = rois.asnumpy()
    assert set(rn[:, 0]) <= {0.0, 1.0}
    assert (rn[:8, 0] == 0).all() and (rn[8:, 0] == 1).all()


def test_psroi_pooling_uniform_input():
    od, ps, gs = 2, 2, 2
    # constant per-channel data → pooled value == channel constant
    x = onp.zeros((1, od * gs * gs, 8, 8), "float32")
    for c in range(od * gs * gs):
        x[0, c] = c
    rois = np.array(onp.array([[0, 0, 0, 7, 7]], "float32"))
    out = npx.psroi_pooling(np.array(x), rois, spatial_scale=1.0,
                            output_dim=od, pooled_size=ps,
                            group_size=gs)
    assert out.shape == (1, od, ps, ps)
    on = out.asnumpy()[0]
    # bin (i,j) of output channel c reads input channel c*4 + i*2 + j
    for c in range(od):
        for i in range(ps):
            for j in range(ps):
                assert on[c, i, j] == c * 4 + i * 2 + j


def test_deformable_psroi_pooling_no_trans_matches_psroi_shape():
    od, ps = 2, 3
    x = _r(1, od * ps * ps, 12, 12, lo=0, hi=1)
    rois = np.array(onp.array([[0, 1, 1, 10, 10]], "float32"))
    trans = np.zeros((1, 2, ps, ps))
    out = npx.deformable_psroi_pooling(
        x, rois, trans, spatial_scale=1.0, output_dim=od,
        group_size=ps, pooled_size=ps, trans_std=0.1, no_trans=True)
    assert out.shape == (1, od, ps, ps)
    assert onp.isfinite(out.asnumpy()).all()
    # nonzero offsets change the result
    trans2 = np.array(onp.full((1, 2, ps, ps), 2.0, "float32"))
    out2 = npx.deformable_psroi_pooling(
        x, rois, trans2, spatial_scale=1.0, output_dim=od,
        group_size=ps, pooled_size=ps, trans_std=0.5, no_trans=False)
    assert not onp.allclose(out.asnumpy(), out2.asnumpy())


def test_rroi_align_axis_aligned_matches_crop():
    x = onp.arange(64, dtype="float32").reshape(1, 1, 8, 8)
    # axis-aligned roi centered at (3.5, 3.5), 8x8, no rotation
    rois = np.array(onp.array([[0, 3.5, 3.5, 8, 8, 0.0]], "float32"))
    out = npx.rroi_align(np.array(x), rois, pooled_size=2,
                         spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    on = out.asnumpy()[0, 0]
    # 2x2 bins sample at (±2, ±2) around center: symmetric values
    assert on[0, 0] < on[0, 1] and on[0, 0] < on[1, 0]
    # +90° rotation maps local (lx,ly) → (−ly,lx): bin (0,0) now samples
    # where bin (0,1) sampled in the unrotated roi
    rois90 = np.array(onp.array([[0, 3.5, 3.5, 8, 8, 90.0]], "float32"))
    out90 = npx.rroi_align(np.array(x), rois90, pooled_size=2,
                           spatial_scale=1.0)
    onp.testing.assert_allclose(out90.asnumpy()[0, 0, 0, 0],
                                on[0, 1], rtol=1e-4)


def test_mrcnn_mask_target():
    b, r, m, hh, ww, c = 1, 2, 3, 16, 16, 4
    rois = np.array(onp.array(
        [[[0, 0, 15, 15], [4, 4, 12, 12]]], "float32"))
    gt = onp.zeros((b, m, hh, ww), "float32")
    gt[0, 1, :, :] = 1.0                 # mask 1 is all-ones
    matches = np.array(onp.array([[1, 0]], "int32"))
    cls_t = np.array(onp.array([[2, 1]], "int32"))
    targets, weights = npx.mrcnn_mask_target(
        rois, np.array(gt), matches, cls_t, num_rois=r,
        num_classes=c, mask_size=(7, 7))
    assert targets.shape == (b, r, c, 7, 7)
    assert weights.shape == (b, r, c, 7, 7)
    tn, wn = targets.asnumpy(), weights.asnumpy()
    # roi 0 matched all-ones mask, class 2 → its slice is 1, others 0
    onp.testing.assert_allclose(tn[0, 0, 2], onp.ones((7, 7)))
    assert tn[0, 0, 1].max() == 0.0
    onp.testing.assert_allclose(wn[0, 0, 2], onp.ones((7, 7)))
    assert wn[0, 0, 0].max() == 0.0
    # roi 1 matched all-zeros mask → target zero, weight on class 1
    assert tn[0, 1].max() == 0.0
    onp.testing.assert_allclose(wn[0, 1, 1], onp.ones((7, 7)))


def test_modulated_deformable_convolution():
    x = _r(1, 4, 6, 6)
    wgt = _r(2, 4, 3, 3, seed=1)
    off = np.zeros((1, 2 * 9, 4, 4))
    mask = np.ones((1, 9, 4, 4))
    out = npx.modulated_deformable_convolution(
        x, off, mask, wgt, kernel=(3, 3), num_filter=2, no_bias=True)
    # zero offsets + unit mask == plain convolution
    ref = npx.convolution(x, wgt, kernel=(3, 3), num_filter=2,
                          no_bias=True)
    onp.testing.assert_allclose(out.asnumpy(), ref.asnumpy(),
                                rtol=1e-4, atol=1e-5)
