"""Broad np-namespace correctness sweep against NumPy goldens (reference
model: tests/python/unittest/test_numpy_op.py — the largest suite; this is
the parametrized equivalent over the jnp-mapped namespace)."""
import numpy as onp
import pytest

from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


RS = onp.random.RandomState(42)
X = RS.uniform(0.2, 2.0, (4, 5)).astype(onp.float32)       # positive
XS = RS.uniform(-0.9, 0.9, (4, 5)).astype(onp.float32)     # in (-1, 1)
Y = RS.uniform(0.2, 2.0, (4, 5)).astype(onp.float32)
XI = RS.randint(0, 10, (4, 5)).astype(onp.int32)

UNARY = [
    ("negative", XS), ("abs", XS), ("absolute", XS), ("sign", XS),
    ("rint", XS), ("floor", XS), ("ceil", XS), ("trunc", XS), ("sqrt", X),
    ("cbrt", X), ("square", XS), ("reciprocal", X), ("exp", XS),
    ("expm1", XS), ("log", X), ("log2", X), ("log10", X), ("log1p", X),
    ("sin", XS), ("cos", XS), ("tan", XS), ("arcsin", XS), ("arccos", XS),
    ("arctan", XS), ("sinh", XS), ("cosh", XS), ("tanh", XS),
    ("arcsinh", XS), ("arctanh", XS), ("degrees", XS), ("radians", XS),
]

BINARY = [
    ("add", X, Y), ("subtract", X, Y), ("multiply", X, Y),
    ("divide", X, Y), ("true_divide", X, Y), ("power", X, Y),
    ("maximum", X, Y), ("minimum", X, Y), ("fmax", X, Y), ("fmin", X, Y),
    ("hypot", X, Y), ("arctan2", XS, Y), ("logaddexp", X, Y),
    ("copysign", X, XS), ("fmod", X, Y), ("remainder", X, Y),
    ("floor_divide", X, Y), ("gcd", XI, XI.T.reshape(4, 5)),
    ("lcm", XI, XI.T.reshape(4, 5)), ("heaviside", XS, Y),
    ("nextafter", X, Y), ("ldexp", X, XI % 3),
]  # nextafter added to the jnp-mapped list alongside this test

REDUCTIONS = [
    ("sum", {}), ("mean", {}), ("std", {}), ("var", {}), ("min", {}),
    ("max", {}), ("prod", {}), ("argmin", {}), ("argmax", {}),
    ("sum", {"axis": 0}), ("mean", {"axis": 1}), ("std", {"axis": 0}),
    ("cumsum", {"axis": 1}), ("cumprod", {"axis": 0}),
    ("median", {}), ("ptp", {}), ("any", {}), ("all", {}),
]


@pytest.mark.parametrize("name,x", UNARY, ids=[u[0] for u in UNARY])
def test_unary_matches_numpy(name, x):
    got = A(getattr(mnp, name)(mnp.array(x)))
    want = getattr(onp, name)(x)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("name,x,y", BINARY, ids=[b[0] for b in BINARY])
def test_binary_matches_numpy(name, x, y):
    got = A(getattr(mnp, name)(mnp.array(x), mnp.array(y)))
    want = getattr(onp, name)(x, y)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("name,kw", REDUCTIONS,
                         ids=[f"{r[0]}-{r[1]}" for r in REDUCTIONS])
def test_reduction_matches_numpy(name, kw):
    got = A(getattr(mnp, name)(mnp.array(X), **kw))
    want = getattr(onp, name)(X, **kw)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


SHAPE_OPS = [
    ("reshape", ((20,),), {}),
    ("transpose", (), {}),
    ("swapaxes", (0, 1), {}),
    ("expand_dims", (1,), {}),
    ("squeeze", (), {}),
    ("flip", (), {"axis": 0}),
    ("roll", (2,), {"axis": 1}),
    ("rot90", (), {}),
    ("tile", ((2, 1),), {}),
    ("repeat", (2,), {"axis": 0}),
]


@pytest.mark.parametrize("name,args,kw", SHAPE_OPS,
                         ids=[s[0] for s in SHAPE_OPS])
def test_shape_op_matches_numpy(name, args, kw):
    x = X if name != "squeeze" else X.reshape(4, 1, 5)
    got = A(getattr(mnp, name)(mnp.array(x), *args, **kw))
    want = getattr(onp, name)(x, *args, **kw)
    onp.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name", ["sqrt", "exp", "log", "tanh", "square"])
def test_unary_grad_matches_analytic(name):
    derivs = {
        "sqrt": lambda x: 0.5 / onp.sqrt(x),
        "exp": onp.exp,
        "log": lambda x: 1.0 / x,
        "tanh": lambda x: 1 - onp.tanh(x) ** 2,
        "square": lambda x: 2 * x,
    }
    a = NDArray(X)
    a.attach_grad()
    with autograd.record():
        out = getattr(mnp, name)(a).sum()
    out.backward()
    onp.testing.assert_allclose(A(a.grad), derivs[name](X),
                                rtol=1e-4, atol=1e-5)


def test_einsum_matches_numpy():
    a = RS.randn(3, 4).astype(onp.float32)
    b = RS.randn(4, 5).astype(onp.float32)
    got = A(mnp.einsum("ij,jk->ik", mnp.array(a), mnp.array(b)))
    onp.testing.assert_allclose(got, onp.einsum("ij,jk->ik", a, b),
                                rtol=1e-4, atol=1e-5)


def test_linalg_sweep():
    m = RS.randn(4, 4).astype(onp.float32)
    spd = m @ m.T + 4 * onp.eye(4, dtype=onp.float32)
    onp.testing.assert_allclose(
        A(mnp.linalg.inv(mnp.array(spd))) @ spd, onp.eye(4),
        rtol=1e-3, atol=1e-3)
    onp.testing.assert_allclose(
        A(mnp.linalg.det(mnp.array(spd))), onp.linalg.det(spd), rtol=1e-3)
    l = A(mnp.linalg.cholesky(mnp.array(spd)))
    onp.testing.assert_allclose(l @ l.T, spd, rtol=1e-3, atol=1e-3)
    q, r = mnp.linalg.qr(mnp.array(m))
    onp.testing.assert_allclose(A(q) @ A(r), m, rtol=1e-3, atol=1e-3)
    w = A(mnp.linalg.eigvalsh(mnp.array(spd)))
    onp.testing.assert_allclose(sorted(w), sorted(onp.linalg.eigvalsh(spd)),
                                rtol=1e-3)


def test_sort_search_sweep():
    x = RS.randn(5, 6).astype(onp.float32)
    onp.testing.assert_array_equal(A(mnp.sort(mnp.array(x), axis=1)),
                                   onp.sort(x, axis=1))
    onp.testing.assert_array_equal(A(mnp.argsort(mnp.array(x), axis=0)),
                                   onp.argsort(x, axis=0))
    onp.testing.assert_array_equal(
        A(mnp.searchsorted(mnp.array(onp.sort(x[0])), mnp.array(x[1]))),
        onp.searchsorted(onp.sort(x[0]), x[1]))


def test_set_ops_sweep():
    a = onp.array([3, 1, 2, 3, 1], onp.int32)
    b = onp.array([2, 3, 9], onp.int32)
    onp.testing.assert_array_equal(A(mnp.unique(mnp.array(a))),
                                   onp.unique(a))
    onp.testing.assert_array_equal(A(mnp.intersect1d(mnp.array(a),
                                                     mnp.array(b))),
                                   onp.intersect1d(a, b))
    onp.testing.assert_array_equal(A(mnp.union1d(mnp.array(a),
                                                 mnp.array(b))),
                                   onp.union1d(a, b))
    onp.testing.assert_array_equal(A(mnp.isin(mnp.array(a), mnp.array(b))),
                                   onp.isin(a, b))


@pytest.mark.parametrize("dtype", ["float16", "bfloat16", "float32"])
def test_dtype_sweep_binary(dtype):
    import jax.numpy as jnp

    dt = jnp.bfloat16 if dtype == "bfloat16" else onp.dtype(dtype)
    a = mnp.array(X, dtype=dt)
    b = mnp.array(Y, dtype=dt)
    out = a * b + a
    assert dtype in str(out.dtype)  # bf16 dtype surfaces as the scalar type
    onp.testing.assert_allclose(A(out).astype(onp.float32), X * Y + X,
                                rtol=2e-2, atol=2e-2)


def test_histogram_bincount_digitize():
    x = RS.randint(0, 8, (50,)).astype(onp.int32)
    onp.testing.assert_array_equal(A(mnp.bincount(mnp.array(x))),
                                   onp.bincount(x))
    h, e = mnp.histogram(mnp.array(x.astype(onp.float32)), bins=4)
    hn, en = onp.histogram(x.astype(onp.float32), bins=4)
    onp.testing.assert_array_equal(A(h), hn)
    onp.testing.assert_allclose(A(e), en, rtol=1e-5)
