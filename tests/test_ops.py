"""Operator correctness against NumPy golden values (modeled on the
reference's test_numpy_op.py / test_operator.py pattern, SURVEY.md §4)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, npx
from incubator_mxnet_tpu.test_utils import assert_almost_equal


def _rand(*shape):
    return onp.random.RandomState(sum(shape) + 7).uniform(-2, 2, shape) \
        .astype("float32")


@pytest.mark.parametrize("name", [
    "exp", "log1p", "sqrt", "sin", "cos", "tanh", "abs", "sign", "floor",
    "ceil", "square",
])
def test_unary_vs_numpy(name):
    x = onp.abs(_rand(3, 4)) + 0.5 if name in ("log1p", "sqrt") else _rand(3, 4)
    got = getattr(np, name)(np.array(x)).asnumpy()
    want = getattr(onp, name)(x)
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["add", "subtract", "multiply", "maximum",
                                  "minimum", "arctan2", "hypot"])
def test_binary_vs_numpy(name):
    a, b = _rand(3, 4), _rand(3, 4)
    got = getattr(np, name)(np.array(a), np.array(b)).asnumpy()
    assert_almost_equal(got, getattr(onp, name)(a, b), rtol=1e-5, atol=1e-6)


def test_broadcasting():
    a, b = _rand(3, 1, 4), _rand(2, 1)
    got = (np.array(a) * np.array(b)).asnumpy()
    assert_almost_equal(got, a * b, rtol=1e-6)


@pytest.mark.parametrize("red,kwargs", [
    ("sum", {}), ("mean", {}), ("max", {}), ("min", {}), ("prod", {}),
    ("std", {}), ("var", {}), ("sum", {"axis": 1}), ("mean", {"axis": 0}),
    ("sum", {"axis": (0, 2), "keepdims": True}),
])
def test_reductions_vs_numpy(red, kwargs):
    x = _rand(2, 3, 4)
    got = getattr(np, red)(np.array(x), **kwargs).asnumpy()
    want = getattr(onp, red)(x, **kwargs)
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)


def test_matmul_einsum_tensordot():
    a, b = _rand(3, 4), _rand(4, 5)
    assert_almost_equal(np.matmul(np.array(a), np.array(b)).asnumpy(), a @ b,
                        rtol=1e-5)
    assert_almost_equal(np.dot(np.array(a), np.array(b)).asnumpy(), a @ b,
                        rtol=1e-5)
    got = np.einsum("ij,jk->ik", np.array(a), np.array(b)).asnumpy()
    assert_almost_equal(got, a @ b, rtol=1e-5)
    t = np.tensordot(np.array(a), np.array(b), axes=1).asnumpy()
    assert_almost_equal(t, a @ b, rtol=1e-5)


def test_concat_stack_split():
    a, b = _rand(2, 3), _rand(2, 3)
    c = np.concatenate([np.array(a), np.array(b)], axis=0)
    assert_almost_equal(c.asnumpy(), onp.concatenate([a, b], axis=0))
    s = np.stack([np.array(a), np.array(b)], axis=1)
    assert s.shape == (2, 2, 3)
    parts = np.split(np.array(a), 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1)


def test_sort_argsort_topk():
    x = _rand(4, 6)
    assert_almost_equal(np.sort(np.array(x), axis=1).asnumpy(),
                        onp.sort(x, axis=1))
    assert (np.argsort(np.array(x), axis=1).asnumpy()
            == onp.argsort(x, axis=1)).all()
    vals = npx.topk(np.array(x), k=2, ret_typ="value", axis=1).asnumpy()
    want = onp.sort(x, axis=1)[:, -2:][:, ::-1]
    assert_almost_equal(vals, want)


def test_where_clip_round():
    x = _rand(3, 3)
    got = np.where(np.array(x) > 0, np.array(x), np.zeros((3, 3))).asnumpy()
    assert_almost_equal(got, onp.where(x > 0, x, 0))
    assert_almost_equal(np.clip(np.array(x), -1, 1).asnumpy(),
                        onp.clip(x, -1, 1))


def test_linalg():
    a = _rand(4, 4)
    spd = a @ a.T + 4 * onp.eye(4, dtype="float32")
    assert_almost_equal(np.linalg.inv(np.array(spd)).asnumpy(),
                        onp.linalg.inv(spd), rtol=1e-3, atol=1e-4)
    l = np.linalg.cholesky(np.array(spd)).asnumpy()
    assert_almost_equal(l @ l.T, spd, rtol=1e-4, atol=1e-4)
    assert_almost_equal(np.linalg.norm(np.array(a)).asnumpy(),
                        onp.linalg.norm(a), rtol=1e-5)
    det = np.linalg.det(np.array(spd)).asnumpy()
    assert_almost_equal(det, onp.linalg.det(spd), rtol=1e-3)
    q, r = np.linalg.qr(np.array(a))
    assert_almost_equal((q @ r).asnumpy(), a, rtol=1e-4, atol=1e-5)


def test_npx_softmax_family():
    x = _rand(3, 5)
    got = npx.softmax(np.array(x), axis=-1).asnumpy()
    e = onp.exp(x - x.max(-1, keepdims=True))
    want = e / e.sum(-1, keepdims=True)
    assert_almost_equal(got, want, rtol=1e-5, atol=1e-6)
    got_log = npx.log_softmax(np.array(x), axis=-1).asnumpy()
    assert_almost_equal(got_log, onp.log(want), rtol=1e-4, atol=1e-5)
    # masked softmax zeros masked positions
    mask = onp.array([[1, 1, 0, 0, 0]] * 3, dtype="bool")
    got_m = npx.masked_softmax(np.array(x), np.array(mask)).asnumpy()
    assert (got_m[:, 2:] == 0).all()
    assert_almost_equal(got_m.sum(-1), onp.ones(3), rtol=1e-5)


def test_npx_one_hot_pick():
    idx = np.array([0, 2, 1], dtype="int32")
    oh = npx.one_hot(idx, 4).asnumpy()
    assert oh.shape == (3, 4)
    assert (oh.argmax(-1) == onp.array([0, 2, 1])).all()
    x = _rand(3, 4)
    picked = npx.pick(np.array(x), np.array([1, 2, 3]), axis=1).asnumpy()
    assert_almost_equal(picked, x[onp.arange(3), [1, 2, 3]])


def test_npx_fully_connected():
    x, w, b = _rand(2, 5), _rand(3, 5), _rand(3)
    got = npx.fully_connected(np.array(x), np.array(w), np.array(b),
                              num_hidden=3).asnumpy()
    assert_almost_equal(got, x @ w.T + b, rtol=1e-5)


def test_npx_convolution_vs_manual():
    x = _rand(1, 1, 5, 5)
    w = _rand(1, 1, 3, 3)
    got = npx.convolution(np.array(x), np.array(w), None, kernel=(3, 3),
                          num_filter=1, no_bias=True).asnumpy()
    # manual valid conv
    want = onp.zeros((1, 1, 3, 3), dtype="float32")
    for i in range(3):
        for j in range(3):
            want[0, 0, i, j] = (x[0, 0, i:i + 3, j:j + 3] * w[0, 0]).sum()
    assert_almost_equal(got, want, rtol=1e-4, atol=1e-5)


def test_npx_batch_dot():
    a, b = _rand(4, 2, 3), _rand(4, 3, 5)
    got = npx.batch_dot(np.array(a), np.array(b)).asnumpy()
    assert_almost_equal(got, onp.einsum("bij,bjk->bik", a, b), rtol=1e-5)


def test_npx_sequence_mask():
    x = np.ones((4, 2, 3))  # (T, N, ...)
    out = npx.sequence_mask(x, sequence_length=np.array([2, 4]),
                            use_sequence_length=True, value=-1.0).asnumpy()
    assert (out[:2, 0] == 1).all()
    assert (out[2:, 0] == -1).all()
    assert (out[:, 1] == 1).all()


def test_npx_rnn_shapes():
    T, N, C, H = 5, 3, 4, 6
    x = np.array(_rand(T, N, C))
    for mode, nst in [("lstm", 2), ("gru", 1), ("rnn_tanh", 1)]:
        psize = npx.rnn_param_size(mode, 2, C, H, bidirectional=False)
        params = np.array(_rand(psize))
        h0 = np.zeros((2, N, H))
        c0 = np.zeros((2, N, H)) if mode == "lstm" else None
        out = npx.rnn(data=x, parameters=params, state=h0, state_cell=c0,
                      mode=mode, state_size=H, num_layers=2,
                      state_outputs=True)
        assert out[0].shape == (T, N, H)
        assert out[1].shape == (2, N, H)
        if mode == "lstm":
            assert out[2].shape == (2, N, H)


def test_npx_reshape_magic():
    x = np.ones((2, 3, 4, 5))
    assert npx.reshape(x, (-2,)).shape == (2, 3, 4, 5)
    assert npx.reshape(x, (0, -3, 0)).shape == (2, 12, 5)
    assert npx.reshape(x, (-1,)).shape == (120,)
    assert npx.reshape(x, (0, 0, -5)).shape == (2, 3, 20)


def test_npx_gather_scatter():
    x = np.array(_rand(3, 4))
    idx = np.array([[0, 2], [1, 3]], dtype="int32")
    got = npx.gather_nd(x, idx).asnumpy()
    assert_almost_equal(got, x.asnumpy()[[0, 2], [1, 3]])


def test_random_ops():
    mx.random.seed(7)
    u = np.random.uniform(0, 1, size=(1000,))
    assert 0.4 < float(u.mean().item()) < 0.6
    n = np.random.normal(0, 1, size=(1000,))
    assert abs(float(n.mean().item())) < 0.15
    r = np.random.randint(0, 10, size=(100,))
    assert int(r.min().item()) >= 0 and int(r.max().item()) < 10
    # determinism under fixed seed
    mx.random.seed(42)
    a = np.random.uniform(size=(5,)).asnumpy()
    mx.random.seed(42)
    b = np.random.uniform(size=(5,)).asnumpy()
    assert_almost_equal(a, b)


def test_control_flow():
    data = np.array(_rand(4, 3))

    def body(x, states):
        return x * 2, [states[0] + x.sum()]

    outs, states = npx.foreach(body, data, [np.zeros(())])
    assert outs.shape == (4, 3)
    assert_almost_equal(states[0].asnumpy(),
                        data.asnumpy().sum(), rtol=1e-5)

    def cond(i, total):
        return i < 5

    def func(i, total):
        return None, (i + 1, total + i)

    _, (i, total) = npx.while_loop(cond, func, (np.array(0), np.array(0)),
                                   max_iterations=10)
    assert int(i.item()) == 5
    assert int(total.item()) == 10

    out = npx.cond(np.array(True), lambda: np.ones(2), lambda: np.zeros(2))
    assert out.asnumpy().sum() == 2
