"""io facade tests: ImageRecordIter / MNISTIter / gluon.utils parity."""
import gzip
import struct

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import image, recordio


def _make_rec(tmp_path, n=10, size=12):
    path = str(tmp_path / "imgs.rec")
    w = recordio.MXRecordIO(path, "w")
    rng = onp.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3)).astype(onp.uint8)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 3), i, 0),
                              image.imencode(img)))
    w.close()
    return path


def test_image_record_iter(tmp_path):
    rec = _make_rec(tmp_path)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 8, 8),
                               batch_size=5, shuffle=True,
                               rand_mirror=True, mean_r=0.5)
    batch = next(iter(it))
    data = batch.data[0] if isinstance(batch.data, (list, tuple)) \
        else batch.data
    assert tuple(data.shape) == (5, 3, 8, 8)


def test_mnist_iter(tmp_path):
    rng = onp.random.RandomState(0)
    imgs = rng.randint(0, 255, (20, 28, 28)).astype(onp.uint8)
    labels = rng.randint(0, 10, (20,)).astype(onp.uint8)
    img_path = str(tmp_path / "train-images-idx3-ubyte.gz")
    lbl_path = str(tmp_path / "train-labels-idx1-ubyte.gz")
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 20, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", 20))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=4,
                         flat=True)
    batch = it.next()
    assert tuple(batch.data[0].shape) == (4, 784)
    onp.testing.assert_allclose(batch.data[0].asnumpy()[0],
                                imgs[0].reshape(-1) / 255.0, rtol=1e-6)
    onp.testing.assert_allclose(batch.label[0].asnumpy(),
                                labels[:4].astype(onp.float32))


def test_mnist_iter_missing_args():
    with pytest.raises(ValueError):
        mx.io.MNISTIter(batch_size=4)


def test_shape_is_known():
    gu = mx.gluon.utils
    assert gu.shape_is_known((2, 3))
    assert not gu.shape_is_known((2, 0))
    assert not gu.shape_is_known(None)
    assert gu.shape_is_known(5)


def test_split_rnn_params_lstm():
    gu = mx.gluon.utils
    H, I = 3, 2
    n = 4 * H * I + 4 * H * H + 8 * H  # 1-layer lstm packed size
    params = onp.arange(n, dtype=onp.float32)
    out = gu.split_rnn_params(mx.nd.array(params), "lstm", 1, I, H)
    assert out["l0_i2h_weight"].shape == (4 * H, I)
    assert out["l0_h2h_weight"].shape == (4 * H, H)
    assert out["l0_i2h_bias"].shape == (4 * H,)
    # packed order: weights first then biases (fused rnn layout)
    onp.testing.assert_array_equal(
        out["l0_i2h_weight"].asnumpy().reshape(-1),
        params[:4 * H * I])


def test_split_rnn_params_size_mismatch_raises():
    gu = mx.gluon.utils
    params = onp.zeros(999, onp.float32)
    with pytest.raises(ValueError, match="consumes"):
        gu.split_rnn_params(mx.nd.array(params), "lstm", 1, 2, 3)


def test_xla_attention_f16_padded_grads_finite():
    import jax
    import jax.numpy as jnp
    import importlib

    fa = importlib.import_module("incubator_mxnet_tpu.ops.flash_attention")
    q = jnp.asarray(onp.random.RandomState(0)
                    .randn(1, 1, 16, 8).astype(onp.float16))
    lens = jnp.asarray([10], jnp.int32)

    def loss(x):
        return fa.flash_attention(x, x, x, lengths=lens,
                                  impl="xla").astype(jnp.float32).sum()

    g = jax.grad(loss)(q)
    assert bool(jnp.isfinite(g.astype(jnp.float32)).all())
