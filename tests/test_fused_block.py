"""Fused residual+dropout+LayerNorm contract (ops/fused_block.py) and the
layer-norm pallas kernel (ops/layer_norm.py), off-TPU via emulation /
interpret mode — the kernel-vs-chip check lives in test_tpu_consistency."""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from incubator_mxnet_tpu.ops import fused_block as fb
from incubator_mxnet_tpu.ops import layer_norm as ln


def _ref_ln(x, g, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    m = xf.mean(-1, keepdims=True)
    v = xf.var(-1, keepdims=True)
    return ((xf - m) * jax.lax.rsqrt(v + eps) * g + b).astype(x.dtype)


@pytest.fixture
def data():
    rng = onp.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 96, 256), jnp.float32)
    h = jnp.asarray(rng.randn(4, 96, 256), jnp.float32)
    g = jnp.asarray(rng.rand(256) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)
    return x, h, g, b


def test_p0_equals_composed(data):
    x, h, g, b = data
    y = fb.residual_dropout_ln(x, h, g, b, 0.0, jnp.zeros(2, jnp.int32))
    yr = _ref_ln(x + h, g, b)
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(yr),
                                atol=1e-5, rtol=1e-5)


def test_p0_gradients_match_composed(data):
    x, h, g, b = data
    w = jnp.asarray(onp.random.RandomState(0).randn(*x.shape), jnp.float32)
    seeds = jnp.zeros(2, jnp.int32)

    def f(x, h, g, b):
        return (fb.residual_dropout_ln(x, h, g, b, 0.0, seeds) * w).sum()

    def fr(x, h, g, b):
        return (_ref_ln(x + h, g, b) * w).sum()

    got = jax.grad(f, (0, 1, 2, 3))(x, h, g, b)
    want = jax.grad(fr, (0, 1, 2, 3))(x, h, g, b)
    for gt, wt in zip(got, want):
        onp.testing.assert_allclose(onp.asarray(gt), onp.asarray(wt),
                                    atol=2e-4, rtol=2e-3)


def test_dropout_mask_deterministic_and_scaled(data):
    x, h, g, b = data
    seeds = jnp.asarray([11, 7], jnp.int32)
    y1 = fb.residual_dropout_ln(x, h, g, b, 0.4, seeds)
    y2 = fb.residual_dropout_ln(x, h, g, b, 0.4, seeds)
    onp.testing.assert_array_equal(onp.asarray(y1), onp.asarray(y2))
    y3 = fb.residual_dropout_ln(x, h, g, b, 0.4,
                                jnp.asarray([12, 7], jnp.int32))
    assert not onp.allclose(onp.asarray(y1), onp.asarray(y3))


def _emulation_mask(shape, seeds, p):
    """Recreate the exact keep mask `_emulate` derives from the seeds."""
    import jax.random as jr

    key = jr.fold_in(jr.PRNGKey(int(seeds[0])), int(seeds[1]))
    bits = jr.bits(key, shape, jnp.uint32)
    return onp.asarray(bits >= jnp.uint32(fb._threshold(p)))


def test_dropout_keep_fraction_and_scale(data):
    x, h, g, b = data
    p = 0.3
    seeds = jnp.asarray([5, 9], jnp.int32)
    keep = _emulation_mask(x.shape, seeds, p)
    frac = keep.mean()
    assert abs(frac - (1 - p)) < 0.02, frac
    # with x=0, gamma=1, beta=0 the pre-norm sum is mask(h)/(1-p); verify
    # the normalized output matches normalizing that sum directly —
    # dropped positions and the 1/(1-p) scale both observable
    s = onp.where(keep, onp.asarray(h) / (1 - p), 0.0).astype(onp.float32)
    m = s.mean(-1, keepdims=True)
    v = s.var(-1, keepdims=True)
    want = (s - m) / onp.sqrt(v + 1e-5)
    y = fb.residual_dropout_ln(jnp.zeros_like(x), h, jnp.ones(256),
                               jnp.zeros(256), p, seeds)
    onp.testing.assert_allclose(onp.asarray(y), want, atol=2e-4, rtol=1e-3)


def test_grad_zero_where_dropped(data):
    x, h, g, b = data
    p = 0.5
    seeds = jnp.asarray([21, 2], jnp.int32)

    def f(h):
        return (fb.residual_dropout_ln(x, h, g, b, p, seeds)
                .astype(jnp.float32) ** 2).sum()

    dh = onp.asarray(jax.grad(f)(h))
    keep = _emulation_mask(h.shape, seeds, p)
    # gradient w.r.t. h must be EXACTLY zero at dropped positions and
    # overwhelmingly nonzero at kept ones
    onp.testing.assert_array_equal(dh[~keep], 0.0)
    kept_nonzero = (dh[keep] != 0).mean()
    assert kept_nonzero > 0.99, kept_nonzero


def test_ln_kernel_interpret_matches_ref():
    rng = onp.random.RandomState(0)
    x = jnp.asarray(rng.randn(40, 256), jnp.float32)
    g = jnp.asarray(rng.rand(256) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(256), jnp.float32)
    y = ln.layer_norm(x, g, b, interpret=True)
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(_ref_ln(x, g, b)),
                                atol=1e-5, rtol=1e-5)

    def f(x, g, b):
        return (ln.layer_norm(x, g, b, interpret=True) ** 2).sum()

    def fr(x, g, b):
        return (_ref_ln(x, g, b) ** 2).sum()

    got = jax.grad(f, (0, 1, 2))(x, g, b)
    want = jax.grad(fr, (0, 1, 2))(x, g, b)
    for gt, wt in zip(got, want):
        onp.testing.assert_allclose(onp.asarray(gt), onp.asarray(wt),
                                    atol=1e-4, rtol=1e-3)


def test_npx_residual_dropout_ln_fallback_path():
    """Off TPU the npx op composes dropout + layer_norm with the same
    semantics (p=0 deterministic check through the NDArray funnel)."""
    from incubator_mxnet_tpu import np as mxnp
    from incubator_mxnet_tpu import numpy_extension as npx

    rng = onp.random.RandomState(1)
    x = mxnp.array(rng.randn(2, 8, 256).astype("float32"))
    h = mxnp.array(rng.randn(2, 8, 256).astype("float32"))
    g = mxnp.array((rng.rand(256) + 0.5).astype("float32"))
    b = mxnp.array(rng.randn(256).astype("float32"))
    y = npx.residual_dropout_ln(x, h, g, b, p=0.5)  # not training -> p=0
    yr = _ref_ln(jnp.asarray(x.asnumpy() + h.asnumpy()),
                 jnp.asarray(g.asnumpy()), jnp.asarray(b.asnumpy()))
    onp.testing.assert_allclose(onp.asarray(y.asnumpy()), onp.asarray(yr),
                                atol=1e-5, rtol=1e-5)


def test_gelu_dropout_emulation_contract():
    """ops/fused_block.gelu_dropout: p=0 equals exact gelu; p>0 is
    deterministic per seed with 1/(1-p) scaling (off-TPU emulation; the
    kernel-vs-chip check needs a chip host)."""
    rng = onp.random.RandomState(1)
    u = jnp.asarray(rng.randn(64, 256), jnp.float32)
    seeds = jnp.asarray([4, 2], jnp.int32)
    y0 = fb.gelu_dropout(u, 0.0, seeds)
    onp.testing.assert_allclose(
        onp.asarray(y0), onp.asarray(jax.nn.gelu(u, approximate=False)),
        atol=1e-6, rtol=1e-6)
    y1 = fb.gelu_dropout(u, 0.3, seeds)
    y2 = fb.gelu_dropout(u, 0.3, seeds)
    onp.testing.assert_array_equal(onp.asarray(y1), onp.asarray(y2))
    keep = _emulation_mask(u.shape, seeds, 0.3)
    want = onp.where(keep, onp.asarray(y0) / 0.7, 0.0)
    onp.testing.assert_allclose(onp.asarray(y1), want, atol=1e-5)


def test_gelu_dropout_erf_approximation_accuracy():
    """The kernel's Abramowitz-Stegun erf: |err| <= 1.5e-7 in exact
    arithmetic; in f32 evaluation ~4.2e-7 measured — far below bf16/f32
    activation noise."""
    import scipy.special as sp

    z = jnp.linspace(-6.0, 6.0, 4001, dtype=jnp.float32)
    got = onp.asarray(fb._erf_approx(z))
    want = sp.erf(onp.asarray(z, onp.float64))
    assert onp.abs(got - want).max() < 1e-6


def test_bert_cell_matches_reference_composition():
    """TransformerEncoderCell (post-LN, fused residual sites) equals the
    hand-composed ln(x + h) reference in eval mode — pins the fused-op
    integration, not just the op."""
    from incubator_mxnet_tpu import np as mxnp
    from incubator_mxnet_tpu.models.bert import TransformerEncoderCell

    cell = TransformerEncoderCell(units=128, hidden_size=256, num_heads=4,
                                  dropout=0.3)
    cell.initialize()
    x = mxnp.array(onp.random.RandomState(0)
                   .randn(2, 16, 128).astype("float32"))
    out = cell(x)  # eval mode: dropout inactive

    h = cell.attention(x, None, None)
    x1 = _ref_ln(jnp.asarray((x + h).asnumpy()),
                 jnp.asarray(cell.ln1.gamma.data().asnumpy()),
                 jnp.asarray(cell.ln1.beta.data().asnumpy()))
    h2 = cell.ffn(mxnp.array(onp.asarray(x1)))
    want = _ref_ln(jnp.asarray(onp.asarray(x1) + h2.asnumpy()),
                   jnp.asarray(cell.ln2.gamma.data().asnumpy()),
                   jnp.asarray(cell.ln2.beta.data().asnumpy()))
    onp.testing.assert_allclose(onp.asarray(out.asnumpy()),
                                onp.asarray(want), atol=2e-5, rtol=2e-5)


def test_bert_cell_training_dropout_active():
    """In training mode the fused residual sites actually drop (outputs
    differ between draws) and stay finite."""
    from incubator_mxnet_tpu import autograd
    from incubator_mxnet_tpu import np as mxnp
    from incubator_mxnet_tpu.models.bert import TransformerEncoderCell

    cell = TransformerEncoderCell(units=128, hidden_size=256, num_heads=4,
                                  dropout=0.5)
    cell.initialize()
    x = mxnp.array(onp.random.RandomState(1)
                   .randn(2, 16, 128).astype("float32"))
    with autograd.record(train_mode=True):
        o1 = cell(x)
    with autograd.record(train_mode=True):
        o2 = cell(x)
    a1, a2 = o1.asnumpy(), o2.asnumpy()
    assert onp.isfinite(a1).all() and onp.isfinite(a2).all()
    assert not onp.allclose(a1, a2)  # different dropout draws
