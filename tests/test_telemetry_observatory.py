"""Compile & HBM observatory (ISSUE 10): recompile forensics with one
fixture per root cause, program-family ledger completeness over the real
entry points, HBM census attribution + the SC006 crosscheck, the OOM
post-mortem seam, and the off-path overhead gate."""
import glob
import json
import os
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.fault import injection
from incubator_mxnet_tpu.telemetry import compiles, hbm, registry, tracing

import jax
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def _clean_observatory():
    yield
    compiles.disable()
    compiles.reset()
    hbm.disable()
    hbm.disarm_memwatch()
    hbm.reset()
    injection.clear_injection()
    registry.reset()
    tracing.disable()
    tracing.reset()


@pytest.fixture
def armed():
    compiles.enable()
    hbm.enable()
    return None


# ---------------------------------------------------------------------------
# recompile forensics: one fixture per cause, each naming the offender
# ---------------------------------------------------------------------------

def test_recompile_cause_shape(armed):
    f = compiles.ledgered_jit(lambda x: x * 2, family="t.shape")
    f(jnp.ones((4,), "float32"))
    f(jnp.ones((8,), "float32"))
    e1, e2 = compiles.ledger("t.shape")
    assert e1["cause"] == "first"
    assert e2["cause"] == "shape"
    assert "arg 0" in e2["detail"]
    assert "(4,)" in e2["detail"] and "(8,)" in e2["detail"]
    # the recompile surfaced on the labeled counter
    c = registry.counter("mx_jit_recompiles_total",
                         labels={"program": "t.shape", "cause": "shape"})
    assert c.value == 1


def test_recompile_cause_dtype(armed):
    f = compiles.ledgered_jit(lambda x: x + 1, family="t.dtype")
    f(jnp.ones((4,), "float32"))
    f(jnp.ones((4,), "int32"))
    e2 = compiles.ledger("t.dtype")[-1]
    assert e2["cause"] == "dtype"
    assert "arg 0" in e2["detail"]
    assert "float32" in e2["detail"] and "int32" in e2["detail"]


def test_recompile_cause_weak_type(armed):
    f = compiles.ledgered_jit(lambda x: x * 3, family="t.weak")
    f(jnp.ones((), "float32"))          # weak_type=False
    f(jnp.asarray(2.0))                 # weak_type=True, same shape/dtype
    e2 = compiles.ledger("t.weak")[-1]
    assert e2["cause"] == "weak_type", e2
    assert "arg 0" in e2["detail"]


def test_recompile_cause_static_arg(armed):
    f = compiles.ledgered_jit(lambda x, n: x * n, family="t.static",
                              static_argnums=(1,))
    x = jnp.ones((4,), "float32")
    f(x, 3)
    f(x, 4)
    e2 = compiles.ledger("t.static")[-1]
    assert e2["cause"] == "static_arg"
    assert "arg 1" in e2["detail"]
    assert "3" in e2["detail"] and "4" in e2["detail"]


def test_recompile_cause_new_bucket(armed):
    f = compiles.ledgered_jit(
        lambda x: x.sum(), family="t.bucket",
        bucket=lambda args, kwargs: int(args[0].shape[0]))
    f(jnp.ones((4,), "float32"))
    f(jnp.ones((8,), "float32"))        # shape changed, but a NEW bucket
    f(jnp.ones((4,), "float32"))        # cache hit: no entry
    entries = compiles.ledger("t.bucket")
    assert [e["cause"] for e in entries] == ["first", "new_bucket"]
    assert entries[-1]["bucket"] == 8
    rep = compiles.ledger_report()["t.bucket"]
    assert rep["buckets"] == [4, 8]
    assert rep["causes"] == {"new_bucket": 1}


def test_forensics_arity_and_nested_containers(armed):
    # arity change is a static_arg diff, not a crash
    cause, detail = compiles.diagnose(
        compiles.signature_of((jnp.ones((2,)),)),
        compiles.signature_of((jnp.ones((2,)), jnp.ones((2,)))))
    assert cause == "static_arg" and "arity" in detail
    # an aval change nested inside a params tuple still names the leaf
    cause, detail = compiles.diagnose(
        compiles.signature_of(((jnp.ones((2, 2)), jnp.ones((3,))),)),
        compiles.signature_of(((jnp.ones((2, 2)), jnp.ones((5,))),)))
    assert cause == "shape" and "arg 0[1]" in detail


# ---------------------------------------------------------------------------
# ledger completeness: every real program family reports in
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_gpt():
    from incubator_mxnet_tpu.models.gpt import gpt_tiny

    mx.random.seed(7)
    net = gpt_tiny(vocab_size=64, max_length=64, dropout=0.0)
    net.initialize()
    return net


def _drive_engine(net, n_req=2):
    from incubator_mxnet_tpu import serve

    eng = serve.ServeEngine(net, max_slots=2, max_len=64, max_queue=8)
    r = onp.random.RandomState(0)
    reqs = [eng.submit(r.randint(0, 64, (5 + i,)).astype(onp.int32), 4)
            for i in range(n_req)]
    while not all(q.done for q in reqs):
        eng.step()
    return eng


def test_ledger_covers_every_program_family(armed, tiny_gpt):
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import DataParallel

    eng = _drive_engine(tiny_gpt)

    net = gluon.nn.Dense(1, in_units=4)
    net.initialize()
    dp = DataParallel(net, gluon.loss.L2Loss(), mx.optimizer.SGD(0.1))
    X = onp.zeros((8, 4), "float32")
    dp.step(np.array(X), np.array(X[:, :1]))

    # eager cacheable op. The eager jit cache keys on (op fn, static args)
    # — NOT shapes — and is process-global, so any earlier suite module
    # that touched `add` leaves the program warm and no compile event can
    # fire here; evict its entries so this call is a fresh compile.
    from incubator_mxnet_tpu.ndarray import ndarray as nd
    for k in [k for k in nd._JIT_CACHE
              if getattr(k[0], "__name__", "") == "add"]:
        nd._JIT_CACHE.pop(k)
    np.add(np.array([1.0]), np.array([2.0]))

    h = gluon.nn.Dense(2, in_units=3)
    h.initialize()
    h.hybridize()
    x = np.array(onp.ones((1, 3), "float32"))
    h(x)                                          # eager deferred-init pass
    h(x)                                          # cached-graph warmup

    rep = compiles.ledger_report()
    for fam in ("serve.prefill", "serve.decode", "train.DataParallel.step",
                "eager.add", "cached_op:Dense"):
        assert fam in rep, (fam, sorted(rep))
        assert rep[fam]["compiles"] >= 1
        assert rep[fam]["last_fingerprint"], fam
    # cost/memory stats came from XLA's own accounting
    for fam in ("serve.prefill", "serve.decode", "train.DataParallel.step"):
        assert rep[fam]["flops"] and rep[fam]["flops"] > 0, fam
        assert rep[fam]["peak_bytes"] and rep[fam]["peak_bytes"] > 0, fam
    # the serving invariant, now with attribution: exactly first compiles,
    # no steady-state recompile causes on the serve families
    assert not rep["serve.decode"]["causes"]
    # the engine's donation map is on the ledger (KV aliasing contract)
    decode = compiles.ledger("serve.decode")[-1]
    assert decode["donate"], decode["donate"]
    assert eng.xla_program_count() >= 2           # wrapper passthrough


def test_gateway_models_are_attributed_per_model(armed, tiny_gpt):
    from incubator_mxnet_tpu.serve import Gateway, ModelRegistry

    reg = ModelRegistry()
    reg.add("gpta", tiny_gpt, max_slots=2, max_len=64)
    gw = Gateway(reg)
    r = onp.random.RandomState(1)
    gw.generate("gpta", r.randint(0, 64, (6,)).astype(onp.int32), 3)
    rep = compiles.ledger_report()
    assert "serve:gpta.prefill" in rep and "serve:gpta.decode" in rep
    c = hbm.census(top_k=0)
    assert c["owners"].get("serve:gpta.params", 0) > 0
    assert c["owners"].get("serve:gpta.kv_pool", 0) > 0


# ---------------------------------------------------------------------------
# HBM census + SC006 crosscheck
# ---------------------------------------------------------------------------

def test_census_attribution_first_claim_and_weak_binding(armed):
    a = jnp.ones((256,), "float32")               # 1 KiB
    b = jnp.ones((512,), "float32")               # 2 KiB
    alive = {"on": True}

    def probe():
        return {"arrays": [a, b], "detail": {"n": 2},
                "derived": {"half": a.nbytes}} if alive["on"] else None

    hbm.register_owner("t_owner", probe)
    hbm.register_owner("t_dup", lambda: [a])      # second claim loses
    c = hbm.census()
    assert c["owners"]["t_owner"] == a.nbytes + b.nbytes
    assert c["owners"]["t_dup"] == 0
    assert c["derived"]["t_owner.half"] == a.nbytes
    assert c["detail"]["t_owner"] == {"n": 2}
    assert c["total"] >= c["owners"]["t_owner"]
    assert c["unattributed"] == c["total"] - a.nbytes - b.nbytes
    # wide K so other tests' module-scope params can't crowd ours out
    assert any(t["owner"] == "t_owner"
               for t in hbm.census(top_k=4096)["top"])
    # weakly-bound: a dead source drops out instead of erroring
    alive["on"] = False
    assert "t_owner" not in hbm.census()["owners"]
    # armed collector exposes the gauges through the registry report
    text = registry.exposition()
    assert "mx_hbm_live_bytes_total" in text
    assert 'mx_hbm_live_bytes{owner="t_dup"}' in text


def test_watchdog_warns_once_per_streak(armed):
    hoard = []
    warned = []
    for i in range(4):
        hoard.append(jnp.ones((1024 * (i + 1),), "float32"))
        warned.append(hbm.watchdog_observe(window=3, min_growth=1))
    assert warned[2] is True or warned[3] is True
    # one warning per streak: once warned, continued growth stays quiet
    hoard.append(jnp.ones((1 << 16,), "float32"))
    assert hbm.watchdog_observe(window=3, min_growth=1) is False
    assert registry.counter("mx_hbm_watchdog_warnings_total").value == 1


def test_sc006_crosscheck_within_15_percent(armed, tiny_gpt):
    eng = _drive_engine(tiny_gpt)
    xc = eng._sched.slots.hbm_crosscheck()
    assert xc["sc006_bytes"] > 0 and xc["census_bytes"] > 0
    assert 0.85 <= xc["ratio"] <= 1.15, xc
    assert set(xc["owners"]) == {"serve.kv_pool", "serve.params"}


# ---------------------------------------------------------------------------
# OOM post-mortem at the serve_step seam (injected RESOURCE_EXHAUSTED)
# ---------------------------------------------------------------------------

def test_oom_postmortem_dumps_census_and_ledger(armed, tiny_gpt,
                                                tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    eng = _drive_engine(tiny_gpt)                 # populate ledger + owners
    injection.configure_injection({"serve_step": (1.0, 0, 1, "oom")})
    r = onp.random.RandomState(3)
    eng.submit(r.randint(0, 64, (6,)).astype(onp.int32), 3)
    with pytest.raises(injection.InjectedResourceExhausted) as ei:
        eng.step()
    assert hbm.is_resource_exhausted(ei.value)

    dumps = glob.glob(str(tmp_path / "flightrec_oom_serve_step_*.json"))
    assert len(dumps) == 1, dumps
    with open(dumps[0]) as f:
        payload = json.load(f)
    assert payload["error"]["type"] == "InjectedResourceExhausted"
    assert "RESOURCE_EXHAUSTED" in payload["error"]["message"]
    census = payload["context"]["hbm_census"]
    assert census["owners"]["serve.kv_pool"] > 0
    assert census["owners"]["serve.params"] > 0
    assert census["top"], "top-K buffers missing from the post-mortem"
    ledger = payload["context"]["compile_ledger"]
    assert "serve.decode" in ledger["report"]
    assert "serve.prefill" in ledger["report"]
    assert ledger["tail"]["serve.decode"][-1]["cause"] == "first"
    assert registry.counter("mx_oom_postmortems_total",
                            labels={"where": "serve_step"}).value == 1

    # the memwatch CLI renders the dump end to end
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import memwatch
    finally:
        sys.path.pop(0)
    assert memwatch.main(["--postmortem", dumps[0]]) == 0


def test_non_oom_faults_skip_the_postmortem(armed, tiny_gpt, tmp_path,
                                            monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    eng = _drive_engine(tiny_gpt)
    injection.configure_injection({"serve_step": (1.0, 0, 1)})  # plain fault
    r = onp.random.RandomState(4)
    eng.submit(r.randint(0, 64, (6,)).astype(onp.int32), 3)
    with pytest.raises(injection.FaultInjected):
        eng.step()
    assert not glob.glob(str(tmp_path / "flightrec_oom_*.json"))
    assert not hbm.is_resource_exhausted(ValueError("boring"))


def test_postmortem_env_overrides(monkeypatch):
    exc = injection.InjectedResourceExhausted("t", 1)
    # disabled + unset: follows arming (off)
    monkeypatch.delenv("MXNET_OOM_POSTMORTEM", raising=False)
    assert hbm.maybe_oom_postmortem("t", exc) is None
    # MXNET_OOM_POSTMORTEM=0 forces off even when telemetry is armed
    hbm.enable()
    monkeypatch.setenv("MXNET_OOM_POSTMORTEM", "0")
    assert hbm.maybe_oom_postmortem("t", exc) is None


# ---------------------------------------------------------------------------
# off-path contract: MXNET_TELEMETRY unset leaves the hot path alone
# ---------------------------------------------------------------------------

def test_off_path_ledger_is_dead_and_cheap():
    assert not compiles.is_enabled() and not hbm.is_enabled()
    f = jax.jit(lambda a: a * 2.0)
    x = jnp.ones((16, 16), "float32")
    f(x).block_until_ready()                      # warm the cache
    w = compiles.instrument_jit(f, "t.off")
    w(x)                                          # wrapper warm, no entry
    assert compiles.ledger() == {}

    a = np.array(onp.random.RandomState(0).uniform(-1, 1, (16, 16))
                 .astype("float32"))
    np.dot(a, a).wait_to_read()
    iters = 300
    t0 = time.perf_counter()
    for _ in range(iters):
        np.dot(a, a)
    mx.waitall()
    per_op = (time.perf_counter() - t0) / iters

    # the disabled wrapper vs the raw jitted callable: best-of-3 deltas
    # (timing noise on shared CI runners swamps a single measurement)
    def rate(fn):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn(x)
            best = min(best, time.perf_counter() - t0)
        return best / iters

    overhead = rate(w) - rate(f)
    assert overhead < 0.03 * per_op, (overhead, per_op)


def test_knobs_are_documented():
    from incubator_mxnet_tpu import util

    knobs = util.env_knobs()
    assert "MXNET_MEMWATCH_INTERVAL" in knobs
    assert "MXNET_OOM_POSTMORTEM" in knobs


def test_env_knobs_arm_observatory_at_import():
    import subprocess
    import sys

    code = ("import incubator_mxnet_tpu as mx; "
            "from incubator_mxnet_tpu.telemetry import compiles, hbm; "
            "from incubator_mxnet_tpu.ndarray import ndarray as nd; "
            "print(compiles.is_enabled(), hbm.is_enabled(), "
            "nd._COMPILE_HOOK is not None, nd._OOM_HOOK is not None)")
    env = dict(os.environ, MXNET_TELEMETRY="1", JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "True True True True" in out.stdout, out.stdout


def test_roofline_unknown_device_warns_once(caplog):
    import logging

    from incubator_mxnet_tpu.telemetry import roofline

    roofline._WARNED_DEVICES.discard("v99test")
    with caplog.at_level(
            logging.WARNING,
            logger="incubator_mxnet_tpu.telemetry.roofline"):
        r = roofline.analyze([], device="v99test")
        roofline.analyze([], device="v99test")     # second lookup: quiet
    assert r["meta"]["peak_gbs"] is None
    warns = [rec for rec in caplog.records
             if "PEAK_HBM_GBS" in rec.getMessage()]
    assert len(warns) == 1
    msg = warns[0].getMessage()
    assert "v99test" in msg and "v5e" in msg and "peak_gbs=" in msg
