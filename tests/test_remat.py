"""Rematerialization / memory-opt parity (`incubator_mxnet_tpu/remat.py`;
reference: MXNET_BACKWARD_DO_MIRROR + MXNET_MEMORY_OPT,
`docs/static_site/src/pages/api/faq/env_var.md:230-238`, nnvm mirror pass
`src/nnvm/gradient.cc`).

Memory is asserted on the autodiff RESIDUAL ledger
(`jax.ad_checkpoint.saved_residuals` — the forward→backward live set that
remat governs): final HBM peaks belong to XLA, and neither the CPU test
backend nor the tunneled AOT client exposes faithful buffer assignment,
so the residual ledger is the framework-level contract."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, np, optimizer, remat
from incubator_mxnet_tpu.models.bert import bert_small
from incubator_mxnet_tpu.parallel.sharded import DataParallel


def test_resolve_policy_mapping(monkeypatch):
    import jax

    assert remat.resolve_policy(False) == (False, None)
    assert remat.resolve_policy(None) == (False, None)
    active, pol = remat.resolve_policy(True)
    assert active and pol is jax.checkpoint_policies.nothing_saveable
    active, pol = remat.resolve_policy("dots_saveable")
    assert active and pol is jax.checkpoint_policies.dots_saveable
    with pytest.raises(ValueError):
        remat.resolve_policy("no_such_policy")
    # env parity: DO_MIRROR => nothing_saveable; MEMORY_OPT => dots_saveable
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    active, pol = remat.resolve_policy(None)
    assert active and pol is jax.checkpoint_policies.nothing_saveable
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR")
    monkeypatch.setenv("MXNET_MEMORY_OPT", "1")
    active, pol = remat.resolve_policy(None)
    assert active and pol is jax.checkpoint_policies.dots_saveable


def _bert_loss_fn():
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(out, y):
        scores, _ = out
        return ce(scores.reshape(-1, 1000), y.reshape(-1))

    return mlm_loss


def _step_inputs(batch=2, seq=128, seed=0):
    rng = onp.random.RandomState(seed)
    tokens = np.array(rng.randint(0, 1000, (batch, seq)).astype("int32"))
    labels = np.array(rng.randint(0, 1000, (batch, seq)).astype("int32"))
    return tokens, labels


def test_remat_step_matches_plain_numerically():
    """Same seed, same data: the remat step must produce identical losses
    and parameter updates (recompute changes memory, not math)."""
    def run(remat_spec):
        mx.random.seed(123)
        net = bert_small(max_length=128, dropout=0.1)
        net.initialize()
        dp = DataParallel(net, _bert_loss_fn(),
                          optimizer.Adam(learning_rate=1e-3),
                          remat=remat_spec)
        tokens, labels = _step_inputs()
        losses = [float(dp.step(tokens, labels).asnumpy())
                  for _ in range(2)]
        p0 = next(iter(net.collect_params().values())).data().asnumpy()
        return losses, p0

    l_plain, p_plain = run(False)
    l_remat, p_remat = run(True)
    onp.testing.assert_allclose(l_plain, l_remat, rtol=2e-5)
    onp.testing.assert_allclose(p_plain, p_remat, rtol=2e-4, atol=1e-6)


def test_remat_cuts_saved_residuals_under_cap():
    """The BERT-small train forward at seq 512: full remat must keep its
    forward→backward residual bytes under a cap (2× the step INPUTS)
    that the un-remat forward exceeds by an order of magnitude."""
    import jax

    mx.random.seed(5)
    seq = 512
    net = bert_small(max_length=seq, dropout=0.0)
    net.initialize()
    tokens, labels = _step_inputs(batch=4, seq=seq, seed=1)
    net(tokens)  # deferred init
    loss_fn = _bert_loss_fn()

    def saved_for(spec):
        from incubator_mxnet_tpu import remat as _r
        from incubator_mxnet_tpu.ndarray.ndarray import NDArray
        from incubator_mxnet_tpu.random import trace_key_scope
        from incubator_mxnet_tpu.utils.trace import TraceContext
        from incubator_mxnet_tpu import autograd

        params = [p for p in net.collect_params().values()
                  if p.grad_req != "null"]
        arrays = [p.data() for p in params]

        def fwd(param_vals):
            saved = [(a, a._data) for a in arrays]
            for a, v in zip(arrays, param_vals):
                a._data = v
            try:
                with TraceContext(), trace_key_scope(jax.random.key(0)), \
                        autograd.pause(train_mode=True):
                    out = net.forward(tokens)
                    loss = loss_fn(out, labels)
            finally:
                for a, v in saved:
                    a._data = v
            return loss.mean()._data

        wrapped = _r.wrap(fwd, spec)
        return remat.saved_bytes(wrapped, [a._data for a in arrays])

    plain = saved_for(False)
    full = saved_for(True)
    inputs_bytes = sum(
        int(onp.prod(p.shape)) * 4
        for p in net.collect_params().values()) + tokens.size * 4
    cap = 2 * inputs_bytes
    assert plain > cap, (plain, cap)
    assert full < cap, (full, cap)
    assert full < plain / 10, (full, plain)


def test_hybridize_remat_flag_compiles_and_matches():
    """hybridize(remat='dots_saveable') on a gluon net: same outputs."""
    mx.random.seed(9)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, in_units=32, activation="relu"),
            gluon.nn.Dense(32, in_units=64, activation="relu"),
            gluon.nn.Dense(8, in_units=32))
    net.initialize()
    x = np.array(onp.random.RandomState(0)
                 .uniform(-1, 1, (16, 32)).astype("float32"))
    ref = net(x).asnumpy()
    net.hybridize(remat="dots_saveable")
    out1 = net(x).asnumpy()   # eager probe call
    out2 = net(x).asnumpy()   # compiled remat call
    onp.testing.assert_allclose(out1, ref, rtol=1e-6)
    onp.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-6)

    # gradient flow through the remat-compiled graph
    from incubator_mxnet_tpu import autograd

    x.attach_grad()
    with autograd.record():
        y = net(x).sum()
    y.backward()
    assert float(onp.abs(x.grad.asnumpy()).sum()) > 0
