"""mx.serve — continuous-batching inference engine (ISSUE 4).

Two layers of coverage, both deterministic on CPU:

- scheduler-logic tests run against a stub slot decoder (pure host
  arithmetic, no XLA compile — these are the `quick`-marked ones):
  backpressure, policies, deadlines, drain semantics, the fault seam;
- engine tests run a tiny 2-layer GPT through the real compiled
  slot-cache programs: per-request parity with one-at-a-time
  `GPTDecoder.generate`, slot reuse after EOS retirement, out-of-order
  completion, streaming order, and the recompile-count gate (program
  count constant across 3× more requests than slots).
"""
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, serve
from incubator_mxnet_tpu.models.decoding import GPTDecoder
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.serve.scheduler import (DeadlineExceeded,
                                                 EngineClosed, QueueFull,
                                                 Scheduler)

VOCAB = 97


# ---------------------------------------------------------------------------
# scheduler logic against a stub decoder (no XLA, quick)
# ---------------------------------------------------------------------------

class _StubSlots:
    """Slot-decoder stand-in: prefill emits the prompt's length as the
    first token, decode increments — fully deterministic host math."""

    def __init__(self, max_slots=2, max_len=64):
        self.max_slots = max_slots
        self.max_len = max_len
        self.prefills = []

    def prefill(self, slot, prompt_ids, key, temperature=1.0):
        self.prefills.append((slot, len(prompt_ids)))
        return int(len(prompt_ids))

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        pass


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def test_queue_backpressure_raises():
    sched = Scheduler(_StubSlots(max_slots=1), max_queue=2)
    sched.submit(_prompt(4), 4)
    sched.submit(_prompt(5), 4)
    with pytest.raises(QueueFull) as ei:
        sched.submit(_prompt(6), 4)
    assert "capacity" in str(ei.value)
    # backpressure classifies as retryable: front-ends can reuse the
    # framework RetryPolicy unchanged
    from incubator_mxnet_tpu.fault.retry import classify_exception

    assert classify_exception(ei.value) == "retryable"


def test_submit_validation():
    sched = Scheduler(_StubSlots(max_len=16), max_queue=4)
    with pytest.raises(ValueError):
        sched.submit(_prompt(10), 8)       # 18 > max_len 16
    with pytest.raises(ValueError):
        sched.submit(onp.zeros((0,), onp.int32), 4)
    with pytest.raises(ValueError):
        sched.submit(_prompt(4), 0)
    with pytest.raises(ValueError):
        Scheduler(_StubSlots(), policy="weird")


def test_sjf_policy_admits_shortest_first():
    sched = Scheduler(_StubSlots(max_slots=1), policy="sjf", max_queue=8)
    long = sched.submit(_prompt(12), 6)
    short = sched.submit(_prompt(3), 6)
    mid = sched.submit(_prompt(7), 6)
    sched.step()
    assert short.state == "running" and long.state == "queued"
    assert mid.state == "queued"
    # fifo keeps arrival order
    sched2 = Scheduler(_StubSlots(max_slots=1), policy="fifo", max_queue=8)
    a = sched2.submit(_prompt(12), 6)
    b = sched2.submit(_prompt(3), 6)
    sched2.step()
    assert a.state == "running" and b.state == "queued"


def test_deadline_expiry_classifies_retryable():
    sched = Scheduler(_StubSlots(max_slots=1), max_queue=8)
    req = sched.submit(_prompt(4), 4, deadline_s=0.0)
    time.sleep(0.005)
    sched.step()
    assert req.state == "failed"
    with pytest.raises(DeadlineExceeded):
        req.result()
    assert req.error_class == "retryable"
    # a mid-decode deadline frees the slot for the next request
    r2 = sched.submit(_prompt(4), 50, deadline_s=0.02)
    sched.step()
    assert r2.state == "running"
    time.sleep(0.03)
    sched.step()
    assert r2.state == "failed" and sched.n_active == 0


def test_drain_semantics_scheduler():
    sched = Scheduler(_StubSlots(max_slots=1), max_queue=8)
    running = sched.submit(_prompt(4), 3)
    queued = sched.submit(_prompt(5), 3)
    sched.step()
    assert running.state == "running"
    # drain: queued (never admitted) fails loudly, running survives ...
    sched.close(drain=True)
    assert queued.state == "failed"
    with pytest.raises(EngineClosed):
        queued.result()
    with pytest.raises(EngineClosed):
        sched.submit(_prompt(3), 2)
    while not running.done:
        sched.step()
    assert running.result() == [4, 5, 6]   # stub: len, +1, +1
    # ... while drain=False also fails the in-flight slots
    sched2 = Scheduler(_StubSlots(max_slots=1), max_queue=8)
    r = sched2.submit(_prompt(4), 10)
    sched2.step()
    sched2.close(drain=False)
    assert r.state == "failed" and sched2.n_active == 0
    with pytest.raises(EngineClosed):
        r.result()


def test_eos_retirement_and_eviction_metrics():
    from incubator_mxnet_tpu.telemetry import registry

    sched = Scheduler(_StubSlots(max_slots=2), max_queue=8, eos_id=6)
    before = registry.counter(
        "mx_serve_evictions_total",
        "slots freed (EOS / length / deadline / shutdown)").value
    # stub emits len, len+1, ...: a 4-prompt hits eos_id=6 on token 3
    req = sched.submit(_prompt(4), 10)
    while not req.done:
        sched.step()
    assert req.result() == [4, 5, 6]       # truncated AT the eos token
    assert sched.n_active == 0             # slot freed mid-flight
    after = registry.counter(
        "mx_serve_evictions_total",
        "slots freed (EOS / length / deadline / shutdown)").value
    assert after == before + 1


def test_serve_step_fault_seam():
    from incubator_mxnet_tpu import fault

    sched = Scheduler(_StubSlots(), max_queue=4)
    fault.configure_injection("serve_step:1.0:0:1")
    try:
        with pytest.raises(fault.FaultInjected):
            sched.step()
    finally:
        fault.clear_injection()
    sched.step()                           # limit=1: next step is clean


# ---------------------------------------------------------------------------
# real engine over a tiny 2-layer GPT (compiled slot-cache programs)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def net():
    """Spicy random weights (non-degenerate logits) so greedy parity
    exercises token-dependent paths — same recipe as test_gpt.py."""
    mx.random.seed(11)
    m = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(42)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


@pytest.fixture(scope="module")
def ref_dec(net):
    return GPTDecoder(net)


@pytest.fixture(scope="module")
def eng(net):
    """Shared engine: 3 slots so a dozen requests exercise slot reuse."""
    e = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32)
    yield e
    if not e.closed:
        e.shutdown(drain=False)


def _mixed_requests(n, seed=0, lo=3, hi=18, budget_lo=2, budget_hi=12):
    r = onp.random.RandomState(seed)
    prompts = [r.randint(0, VOCAB, (int(r.randint(lo, hi)),))
               .astype(onp.int32) for _ in range(n)]
    budgets = [int(r.randint(budget_lo, budget_hi)) for _ in range(n)]
    return prompts, budgets


def test_serve_matches_one_at_a_time_and_never_recompiles(eng, ref_dec):
    """The acceptance gate: 3× more requests than slots, varied prompt
    lengths and budgets, all flowing through slot reuse — per-request
    output identical to one-at-a-time GPTDecoder.generate, with ZERO
    steady-state recompiles."""
    prompts, budgets = _mixed_requests(9, seed=1)
    # warmup: one request per prefill bucket in play (32 and 64) plus
    # the decode program
    eng.generate(_prompt(5, seed=9), 3)
    eng.generate(onp.resize(_prompt(5, seed=9), 40), 3)
    warm_count = eng.xla_program_count()
    assert warm_count >= 2                 # ≥1 prefill bucket + decode

    handles = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    eng._drive_until(handles)
    for p, b, h in zip(prompts, budgets, handles):
        ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
        got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
        onp.testing.assert_array_equal(got, ref)
    # steady state: same program count, no matter how many requests
    assert eng.xla_program_count() == warm_count


def test_out_of_order_completion(eng, ref_dec):
    """An earlier-submitted long request must not block (or corrupt) a
    later short one — completion is out of order, results per-request."""
    p_long, p_short = _prompt(6, seed=2), _prompt(9, seed=3)
    h_long = eng.submit(p_long, 14)
    h_short = eng.submit(p_short, 2)
    eng._drive_until([h_long, h_short])
    assert h_short.finish_t < h_long.finish_t
    for p, b, h in [(p_long, 14, h_long), (p_short, 2, h_short)]:
        ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
        got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
        onp.testing.assert_array_equal(got, ref)


def test_slot_reuse_after_eos_retirement(eng, ref_dec):
    """EOS retires a slot mid-flight; the freed slot serves the next
    queued request, and its stale cache rows never leak into it."""
    prompts, _ = _mixed_requests(6, seed=4)
    budget = 10
    # pick a real EOS: the token the reference generates 3rd for the
    # first prompt — that request must stop early, the rest run free
    ref0 = ref_dec.generate(prompts[0][None, :], budget).asnumpy()[0]
    eos = int(ref0[prompts[0].size + 2])
    handles = [eng.submit(p, budget, eos_id=eos) for p in prompts]
    eng._drive_until(handles)
    for p, h in zip(prompts, handles):
        ref = ref_dec.generate(p[None, :], budget).asnumpy()[0]
        new = list(ref[p.size:])
        if eos in new:                     # truncated AT first eos
            new = new[:new.index(eos) + 1]
        assert h.result() == [int(t) for t in new]
    # the tagged request really did stop AT its eos, mid-budget
    assert handles[0].tokens[-1] == eos
    assert len(handles[0].tokens) <= 3
    assert eng.n_active == 0


def test_streaming_iter_tokens_ordering(eng, ref_dec):
    p = _prompt(7, seed=5)
    h = eng.submit(p, 8)
    streamed = list(eng.iter_tokens(h))
    ref = ref_dec.generate(p[None, :], 8).asnumpy()[0]
    assert streamed == [int(t) for t in ref[p.size:]]
    assert streamed == h.result()


def test_driver_thread_serves_client_submits(eng, ref_dec):
    """A background driver owns the step loop while this (client) thread
    only submits and streams — the ISSUE's threading contract."""
    eng.start()
    try:
        prompts, budgets = _mixed_requests(5, seed=6)
        handles = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        for h in handles:
            assert h.wait(timeout=120.0), h.state
        for p, b, h in zip(prompts, budgets, handles):
            ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
            got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
            onp.testing.assert_array_equal(got, ref)
    finally:
        eng.stop()


def test_serve_telemetry_series(eng):
    from incubator_mxnet_tpu.telemetry import registry

    rep = registry.report()
    assert rep["mx_serve_ttft_seconds"]["count"] > 0
    assert rep["mx_serve_ttft_seconds"]["min"] > 0
    assert rep["mx_serve_tokens_total"]["value"] > 0
    assert rep["mx_serve_evictions_total"]["value"] > 0
    assert "mx_serve_queue_depth" in rep
    assert "mx_serve_slot_occupancy" in rep
    # bucketed prefill accounts its padding waste
    assert rep["mx_decode_bucket_pad_tokens_total"]["value"] > 0


def test_engine_drain_finishes_running_rejects_new(net, ref_dec):
    """shutdown(drain=True): requests in slots finish completely, the
    never-admitted queue and new submits are rejected loudly."""
    e = serve.ServeEngine(net, max_slots=2, max_len=64, max_queue=8)
    prompts, _ = _mixed_requests(3, seed=7)
    h1 = e.submit(prompts[0], 8)
    h2 = e.submit(prompts[1], 8)
    h3 = e.submit(prompts[2], 8)           # stays queued: only 2 slots
    e.step()                               # admit h1/h2, first decode
    assert h3.state == "queued"
    e.shutdown(drain=True)
    assert h1.done and h2.done and h1.error is None and h2.error is None
    for p, h in [(prompts[0], h1), (prompts[1], h2)]:
        ref = ref_dec.generate(p[None, :], 8).asnumpy()[0]
        got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
        onp.testing.assert_array_equal(got, ref)
    with pytest.raises(EngineClosed):
        h3.result()
    with pytest.raises(EngineClosed):
        e.submit(prompts[0], 4)


@pytest.mark.slow
def test_bench_gpt_serve_contract():
    """The bench lands real numbers under the loud-failure contract:
    nonzero tokens/s and TTFT percentiles, occupancy from the registry
    (reduced trace; the committed extras run the full 32-request one)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)

    tok_s, p50, p99, occ = bench.bench_gpt_serve(
        requests=6, max_slots=3, prompt_max=24, new_max=16,
        mean_interarrival_s=0.01)
    assert tok_s > 0
    assert p99 >= p50 > 0
    assert 0 < occ <= 1
