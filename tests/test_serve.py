"""mx.serve — continuous batching over the PAGED KV cache (ISSUE 4 + 6).

Three layers of coverage, all deterministic on CPU:

- host-only unit tests for the paging machinery (`PageAllocator`,
  `PrefixCache`): alloc/free/refcount, loud `PagePoolExhausted` OOM, and
  the no-silent-eviction-of-shared-pages contract;
- scheduler-logic tests against a stub slot decoder (pure host
  arithmetic, no XLA compile — the `quick`-marked ones): backpressure,
  remaining-chunk SJF, deadlines, drain semantics, the fault seam;
- engine tests running a tiny 2-layer GPT through the real compiled
  paged programs: per-request parity with one-at-a-time
  `GPTDecoder.generate` WITH paging + shared-prefix reuse + chunked
  prefill all active, int8-KV parity within tolerance, slot/page reuse
  after EOS retirement, and the recompile-count gate (program count
  constant across 3× more requests than slots; the traced twin lives in
  test_tracing.py).
"""
import time

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, serve
from incubator_mxnet_tpu.models.decoding import GPTDecoder
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.serve.engine import (PageAllocator,
                                              PagePoolExhausted,
                                              PrefixCache)
from incubator_mxnet_tpu.serve.scheduler import (DeadlineExceeded,
                                                 EngineClosed, QueueFull,
                                                 Scheduler)

VOCAB = 97


# ---------------------------------------------------------------------------
# paging machinery — host-only unit tests (quick)
# ---------------------------------------------------------------------------

def test_page_allocator_alloc_free_refcount():
    a = PageAllocator(n_pages=9, page_tokens=16)        # 8 usable, 0 = trash
    assert a.usable_pages == 8 and a.free_pages == 8 and a.used_pages == 0
    pages = a.alloc(3)
    assert len(pages) == 3 and 0 not in pages           # trash never handed out
    assert a.free_pages == 5 and a.used_pages == 3
    # sharing: a second holder increfs; the first decref keeps the page
    a.incref(pages[:1])
    a.decref(pages[:1])
    assert a.free_pages == 5                            # still referenced
    a.decref(pages)
    assert a.free_pages == 8 and a.used_pages == 0
    # double free is loud
    with pytest.raises(RuntimeError):
        a.decref(pages[:1])
    # incref on a free page is loud (shared page dropped while mapped)
    with pytest.raises(RuntimeError):
        a.incref([pages[0]])


def test_page_allocator_oom_loud():
    a = PageAllocator(n_pages=5, page_tokens=8)         # 4 usable
    held = a.alloc(3)
    with pytest.raises(PagePoolExhausted) as ei:
        a.alloc(2)
    assert "never" in str(ei.value)                     # no silent eviction
    from incubator_mxnet_tpu.fault.retry import classify_exception

    assert classify_exception(ei.value) in ("retryable", "fatal")
    a.decref(held)
    assert len(a.alloc(4)) == 4


def test_prefix_cache_register_lookup_evict():
    a = PageAllocator(n_pages=17, page_tokens=4)        # 16 usable
    cache = PrefixCache(a)
    prompt = onp.arange(11, dtype=onp.int32)            # 2 full pages + tail
    pages = a.alloc(3)
    cache.register(prompt, pages)                       # entries for pages 1,2
    assert len(cache) == 2
    # longest page-aligned PROPER prefix: 8 of 11 tokens
    tokens, shared = cache.lookup(prompt)
    assert tokens == 8 and shared == pages[:2]
    # a prompt extending the same prefix matches it too
    longer = onp.concatenate([prompt[:8], onp.full(6, 90, onp.int32)])
    tokens2, shared2 = cache.lookup(longer)
    assert tokens2 == 8 and shared2 == pages[:2]
    # an identical-length prompt with a different first page misses
    other = onp.concatenate([onp.full(4, 91, onp.int32), prompt[4:]])
    assert cache.lookup(other)[0] == 0
    # the request retires: ITS refs drop, the cache's refs keep pages live
    a.decref(pages)
    assert a.used_pages == 2                            # page 3 freed
    # eviction drops cache refs only — a page shared into a live request
    # survives eviction (refcount stays positive, page NOT reused)
    t, sp = cache.lookup(prompt)
    a.incref(sp)                                        # "live request"
    cache.evict_unused(a.usable_pages)                  # evict everything
    assert len(cache) == 0
    assert a.refcount(sp[0]) == 1 and a.refcount(sp[1]) == 1
    free_before = a.free_pages
    got = a.alloc(free_before)
    assert not set(got) & set(sp)                       # never reused
    a.decref(got)
    a.decref(sp)
    assert a.free_pages == a.usable_pages


def test_prefix_cache_leaves_one_token_for_compute():
    """A fully page-aligned identical prompt still prefills >= 1 token —
    the final token's forward pass produces the first sampled token."""
    a = PageAllocator(n_pages=9, page_tokens=4)
    cache = PrefixCache(a)
    prompt = onp.arange(8, dtype=onp.int32)             # exactly 2 pages
    pages = a.alloc(2)
    cache.register(prompt, pages)                       # both pages cached
    tokens, shared = cache.lookup(prompt)
    assert tokens == 4 and shared == pages[:1]          # proper prefix only


# ---------------------------------------------------------------------------
# scheduler logic against a stub decoder (no XLA, quick)
# ---------------------------------------------------------------------------

class _StubSlots:
    """Paged-interface stand-in: pure host arithmetic over a REAL
    allocator/prefix cache (host-only classes). The final prefill chunk
    emits the prompt's length as the first token, decode increments —
    fully deterministic host math."""

    def __init__(self, max_slots=2, max_len=64, page_tokens=16,
                 prefill_chunk=64):
        self.max_slots = max_slots
        self.max_len = max_len
        self.page_tokens = page_tokens
        self.prefill_chunk = prefill_chunk
        pages_per_slot = -(-max_len // page_tokens)
        self.allocator = PageAllocator(max_slots * pages_per_slot + 1,
                                       page_tokens)
        self.prefix_cache = PrefixCache(self.allocator)
        self.chunks = []                  # (slot, t_start, n) per chunk

    def set_slot_pages(self, slot, pages):
        pass

    def clear_slot(self, slot):
        pass

    def prefill_chunk_step(self, slot, chunk_tokens, t_start, key,
                           temperature=1.0):
        n = len(chunk_tokens)
        self.chunks.append((slot, int(t_start), n))
        return int(t_start) + n, n, 0

    def decode_step(self, last_tok, pos, active, key, temperature):
        return onp.where(active, last_tok + 1, last_tok).astype(onp.int32)

    def xla_program_count(self):
        return 0

    def release(self):
        pass


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


def test_queue_backpressure_raises():
    sched = Scheduler(_StubSlots(max_slots=1), max_queue=2)
    sched.submit(_prompt(4), 4)
    sched.submit(_prompt(5), 4)
    with pytest.raises(QueueFull) as ei:
        sched.submit(_prompt(6), 4)
    assert "capacity" in str(ei.value)
    # backpressure classifies as retryable: front-ends can reuse the
    # framework RetryPolicy unchanged
    from incubator_mxnet_tpu.fault.retry import classify_exception

    assert classify_exception(ei.value) == "retryable"


def test_submit_validation():
    sched = Scheduler(_StubSlots(max_len=16), max_queue=4)
    with pytest.raises(ValueError):
        sched.submit(_prompt(10), 8)       # 18 > max_len 16
    with pytest.raises(ValueError):
        sched.submit(onp.zeros((0,), onp.int32), 4)
    with pytest.raises(ValueError):
        sched.submit(_prompt(4), 0)
    with pytest.raises(ValueError):
        Scheduler(_StubSlots(), policy="weird")


def test_submit_page_budget_loud():
    """A request that could never fit the pool is rejected at submit
    with the loud PagePoolExhausted, not deferred forever."""
    stub = _StubSlots(max_slots=2, max_len=64, page_tokens=16)
    stub.allocator = PageAllocator(3, 16)   # 2 usable pages = 32 tokens
    sched = Scheduler(stub, max_queue=4)
    with pytest.raises(PagePoolExhausted):
        sched.submit(_prompt(30), 20)       # needs 4 pages, pool has 2
    sched.submit(_prompt(10), 10)           # 2 pages: fits


def test_sjf_policy_admits_shortest_first():
    sched = Scheduler(_StubSlots(max_slots=1), policy="sjf", max_queue=8)
    long = sched.submit(_prompt(12), 6)
    short = sched.submit(_prompt(3), 6)
    mid = sched.submit(_prompt(7), 6)
    sched.step()
    assert short.state == "running" and long.state == "queued"
    assert mid.state == "queued"
    # fifo keeps arrival order
    sched2 = Scheduler(_StubSlots(max_slots=1), policy="fifo", max_queue=8)
    a = sched2.submit(_prompt(12), 6)
    b = sched2.submit(_prompt(3), 6)
    sched2.step()
    assert a.state == "running" and b.state == "queued"


def test_sjf_orders_by_remaining_prefill_chunks():
    """ISSUE 6 accounting fix: a LONG prompt whose prefix is cached
    needs fewer remaining chunks than a shorter cold prompt — SJF must
    admit it first."""
    stub = _StubSlots(max_slots=1, max_len=64, page_tokens=8,
                      prefill_chunk=8)
    sched = Scheduler(stub, policy="sjf", max_queue=8)
    long_prompt = _prompt(33, seed=3)       # 5 chunks cold
    short_prompt = _prompt(17, seed=4)      # 3 chunks cold
    # cache the long prompt's first 4 pages: remaining = 1 chunk
    pages = stub.allocator.alloc(4)
    stub.prefix_cache.register(long_prompt[:32], pages)
    h_long = sched.submit(long_prompt, 5)
    h_short = sched.submit(short_prompt, 5)
    sched.step()
    assert h_long.state == "running" and h_short.state == "queued"
    assert h_long.shared_tokens == 32
    # and without the cache entry, plain shortest-first still wins
    stub2 = _StubSlots(max_slots=1, max_len=64, page_tokens=8,
                       prefill_chunk=8)
    sched2 = Scheduler(stub2, policy="sjf", max_queue=8)
    a = sched2.submit(_prompt(33, seed=3), 2)
    b = sched2.submit(_prompt(17, seed=4), 2)
    sched2.step()
    assert b.state == "running" and a.state == "queued"


def test_chunked_prefill_interleaves_with_decode():
    """A long prompt prefills across several steps; an already-running
    request keeps producing a token EVERY step in between (the TTFT-p99
    fix chunking exists for)."""
    stub = _StubSlots(max_slots=2, max_len=64, page_tokens=8,
                      prefill_chunk=8)
    sched = Scheduler(stub, max_queue=8)
    runner = sched.submit(_prompt(4), 20)
    sched.step()      # admit + single-chunk prefill + first decode step
    assert runner.state == "running" and len(runner.tokens) == 2
    long_req = sched.submit(_prompt(33, seed=5), 2)   # 5 chunks
    produced_during_prefill = []
    for _ in range(4):                      # chunks 1..4: still prefilling
        before = len(runner.tokens)
        sched.step()
        produced_during_prefill.append(len(runner.tokens) - before)
        assert long_req.first_token_t is None
    assert all(n == 1 for n in produced_during_prefill)
    sched.step()                            # final chunk: first token
    assert long_req.first_token_t is not None
    assert long_req.tokens[0] == 33         # stub: prompt length
    assert len(stub.chunks) >= 5 + 1


def test_deadline_expiry_classifies_retryable():
    sched = Scheduler(_StubSlots(max_slots=1), max_queue=8)
    req = sched.submit(_prompt(4), 4, deadline_s=0.0)
    time.sleep(0.005)
    sched.step()
    assert req.state == "failed"
    with pytest.raises(DeadlineExceeded):
        req.result()
    assert req.error_class == "retryable"
    # a mid-decode deadline frees the slot for the next request
    r2 = sched.submit(_prompt(4), 50, deadline_s=0.02)
    sched.step()
    assert r2.state == "running"
    time.sleep(0.03)
    sched.step()
    assert r2.state == "failed" and sched.n_active == 0
    # pages went back with the slot
    assert sched.slots.allocator.used_pages == 0


def test_drain_semantics_scheduler():
    sched = Scheduler(_StubSlots(max_slots=1), max_queue=8)
    running = sched.submit(_prompt(4), 3)
    queued = sched.submit(_prompt(5), 3)
    sched.step()
    assert running.state == "running"
    # drain: queued (never admitted) fails loudly, running survives ...
    sched.close(drain=True)
    assert queued.state == "failed"
    with pytest.raises(EngineClosed):
        queued.result()
    with pytest.raises(EngineClosed):
        sched.submit(_prompt(3), 2)
    while not running.done:
        sched.step()
    assert running.result() == [4, 5, 6]   # stub: len, +1, +1
    # ... while drain=False also fails the in-flight slots
    sched2 = Scheduler(_StubSlots(max_slots=1), max_queue=8)
    r = sched2.submit(_prompt(4), 10)
    sched2.step()
    sched2.close(drain=False)
    assert r.state == "failed" and sched2.n_active == 0
    with pytest.raises(EngineClosed):
        r.result()


def test_eos_retirement_and_eviction_metrics():
    from incubator_mxnet_tpu.telemetry import registry

    sched = Scheduler(_StubSlots(max_slots=2), max_queue=8, eos_id=6)
    before = registry.counter(
        "mx_serve_evictions_total",
        "slots freed (EOS / length / deadline / shutdown)").value
    # stub emits len, len+1, ...: a 4-prompt hits eos_id=6 on token 3
    req = sched.submit(_prompt(4), 10)
    while not req.done:
        sched.step()
    assert req.result() == [4, 5, 6]       # truncated AT the eos token
    assert sched.n_active == 0             # slot freed mid-flight
    after = registry.counter(
        "mx_serve_evictions_total",
        "slots freed (EOS / length / deadline / shutdown)").value
    assert after == before + 1


def test_serve_step_fault_seam():
    from incubator_mxnet_tpu import fault

    sched = Scheduler(_StubSlots(), max_queue=4)
    fault.configure_injection("serve_step:1.0:0:1")
    try:
        with pytest.raises(fault.FaultInjected):
            sched.step()
    finally:
        fault.clear_injection()
    sched.step()                           # limit=1: next step is clean


# ---------------------------------------------------------------------------
# real engine over a tiny 2-layer GPT (compiled paged programs)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def net():
    """Spicy random weights (non-degenerate logits) so greedy parity
    exercises token-dependent paths — same recipe as test_gpt.py."""
    mx.random.seed(11)
    m = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(42)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


@pytest.fixture(scope="module")
def ref_dec(net):
    return GPTDecoder(net)


@pytest.fixture(scope="module")
def eng(net):
    """Shared engine: 3 slots so a dozen requests exercise slot reuse."""
    e = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32)
    yield e
    if not e.closed:
        e.shutdown(drain=False)


def _mixed_requests(n, seed=0, lo=3, hi=18, budget_lo=2, budget_hi=12):
    r = onp.random.RandomState(seed)
    prompts = [r.randint(0, VOCAB, (int(r.randint(lo, hi)),))
               .astype(onp.int32) for _ in range(n)]
    budgets = [int(r.randint(budget_lo, budget_hi)) for _ in range(n)]
    return prompts, budgets


def test_serve_matches_one_at_a_time_and_never_recompiles(eng, ref_dec):
    """The acceptance gate: 3× more requests than slots, varied prompt
    lengths and budgets, all flowing through paged slot reuse —
    per-request output identical to one-at-a-time GPTDecoder.generate,
    with ZERO steady-state recompiles (the traced twin of this gate is
    test_tracing.test_real_engine_traced_requests_and_recompile_gate)."""
    prompts, budgets = _mixed_requests(9, seed=1)
    # warmup: one prompt per chunk bucket in play (16/32/64) + decode
    eng.generate(_prompt(5, seed=9), 3)
    eng.generate(onp.resize(_prompt(5, seed=9), 20), 3)
    eng.generate(onp.resize(_prompt(5, seed=9), 40), 3)
    warm_count = eng.xla_program_count()
    assert warm_count >= 2                 # ≥1 chunk bucket + decode

    handles = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    eng._drive_until(handles)
    for p, b, h in zip(prompts, budgets, handles):
        ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
        got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
        onp.testing.assert_array_equal(got, ref)
    # steady state: same program count, no matter how many requests
    assert eng.xla_program_count() == warm_count


def test_paged_prefix_reuse_and_chunking_parity(net, ref_dec):
    """The tentpole end-to-end: small pages, multi-chunk prefill, and a
    SHARED system prompt across requests — outputs stay bit-identical to
    the unpaged reference while the prefix cache takes real hits and the
    program count stays flat."""
    from incubator_mxnet_tpu.telemetry import registry

    e = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32,
                          page_tokens=8, prefill_chunk=16)
    try:
        system = _prompt(24, seed=42)               # 3 shared pages
        tails = [_prompt(int(onp.random.RandomState(i).randint(2, 8)),
                         seed=100 + i) for i in range(8)]
        prompts = [onp.concatenate([system, t]) for t in tails]
        # warm the chunk buckets (8 and 16) + decode out of the gate
        e.generate(prompts[0][:19], 2)
        e.generate(prompts[0][:16], 2)
        warm = e.xla_program_count()
        hits0 = registry.counter("mx_serve_prefix_hits_total").value
        chunks0 = registry.counter("mx_serve_prefill_chunks_total").value
        handles = [e.submit(p, 6) for p in prompts]
        e._drive_until(handles)
        for p, h in zip(prompts, handles):
            ref = ref_dec.generate(p[None, :], 6).asnumpy()[0]
            got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
            onp.testing.assert_array_equal(got, ref)
        hits = registry.counter("mx_serve_prefix_hits_total").value - hits0
        chunks = registry.counter(
            "mx_serve_prefill_chunks_total").value - chunks0
        assert hits >= 4                   # later waves reuse the prefix
        assert chunks >= len(prompts)      # chunked prefill really ran
        assert e.xla_program_count() == warm
        # paged accounting: shared pages counted once, gauge is live
        rep = registry.report()
        assert 0 < rep["mx_serve_page_occupancy"]["value"] <= 1
    finally:
        e.shutdown(drain=False)
    # a drained engine returns every page (cache cleared at shutdown)
    assert e._sched.slots.allocator.used_pages == 0


def test_int8_kv_parity_within_tolerance(net, ref_dec):
    """MXNET_SERVE_KV_DTYPE=int8 equivalent: half the resident KV bytes,
    greedy outputs within tolerance — first token EXACT for single-chunk
    prompts (the chunk attends to its own pre-quantization K/V), and the
    divergence-free prefix covers most of each generation."""
    e8 = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32,
                           kv_dtype="int8")
    efp = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32)
    try:
        prompts, budgets = _mixed_requests(9, seed=1)
        match, total = 0, 0
        for p, b in zip(prompts, budgets):
            out = e8.generate(p, b)[p.size:]
            ref = ref_dec.generate(p[None, :], b).asnumpy()[0][p.size:]
            assert out[0] == ref[0]        # single-chunk first token exact
            k = 0
            for x, y in zip(out, ref):
                if x != y:
                    break
                k += 1
            match += k
            total += len(ref)
        assert match / total >= 0.5, f"int8 drift too large: {match}/{total}"
        # the headline economics: ~4x fewer KV bytes resident per slot
        efp.generate(prompts[0], 2)        # materialize the fp pool
        assert e8.kv_bytes_per_slot < 0.3 * efp.kv_bytes_per_slot
    finally:
        e8.shutdown(drain=False)
        efp.shutdown(drain=False)


def test_out_of_order_completion(eng, ref_dec):
    """An earlier-submitted long request must not block (or corrupt) a
    later short one — completion is out of order, results per-request."""
    p_long, p_short = _prompt(6, seed=2), _prompt(9, seed=3)
    h_long = eng.submit(p_long, 14)
    h_short = eng.submit(p_short, 2)
    eng._drive_until([h_long, h_short])
    assert h_short.finish_t < h_long.finish_t
    for p, b, h in [(p_long, 14, h_long), (p_short, 2, h_short)]:
        ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
        got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
        onp.testing.assert_array_equal(got, ref)


def test_slot_reuse_after_eos_retirement(eng, ref_dec):
    """EOS retires a slot mid-flight; the freed slot (and its pages)
    serve the next queued request, and stale cache rows never leak."""
    prompts, _ = _mixed_requests(6, seed=4)
    budget = 10
    # pick a real EOS: the token the reference generates 3rd for the
    # first prompt — that request must stop early, the rest run free
    ref0 = ref_dec.generate(prompts[0][None, :], budget).asnumpy()[0]
    eos = int(ref0[prompts[0].size + 2])
    handles = [eng.submit(p, budget, eos_id=eos) for p in prompts]
    eng._drive_until(handles)
    for p, h in zip(prompts, handles):
        ref = ref_dec.generate(p[None, :], budget).asnumpy()[0]
        new = list(ref[p.size:])
        if eos in new:                     # truncated AT first eos
            new = new[:new.index(eos) + 1]
        assert h.result() == [int(t) for t in new]
    # the tagged request really did stop AT its eos, mid-budget
    assert handles[0].tokens[-1] == eos
    assert len(handles[0].tokens) <= 3
    assert eng.n_active == 0


def test_streaming_iter_tokens_ordering(eng, ref_dec):
    p = _prompt(7, seed=5)
    h = eng.submit(p, 8)
    streamed = list(eng.iter_tokens(h))
    ref = ref_dec.generate(p[None, :], 8).asnumpy()[0]
    assert streamed == [int(t) for t in ref[p.size:]]
    assert streamed == h.result()


def test_driver_thread_serves_client_submits(eng, ref_dec):
    """A background driver owns the step loop while this (client) thread
    only submits and streams — the ISSUE's threading contract."""
    eng.start()
    try:
        prompts, budgets = _mixed_requests(5, seed=6)
        handles = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        for h in handles:
            assert h.wait(timeout=120.0), h.state
        for p, b, h in zip(prompts, budgets, handles):
            ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
            got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
            onp.testing.assert_array_equal(got, ref)
    finally:
        eng.stop()


def test_serve_telemetry_series(eng):
    from incubator_mxnet_tpu.telemetry import registry

    rep = registry.report()
    assert rep["mx_serve_ttft_seconds"]["count"] > 0
    assert rep["mx_serve_ttft_seconds"]["min"] > 0
    assert rep["mx_serve_tokens_total"]["value"] > 0
    assert rep["mx_serve_evictions_total"]["value"] > 0
    assert "mx_serve_queue_depth" in rep
    assert "mx_serve_slot_occupancy" in rep
    # ISSUE 6 series: paged allocation + chunked prefill accounting
    assert "mx_serve_page_occupancy" in rep
    assert rep["mx_serve_prefill_chunks_total"]["value"] > 0
    assert "mx_serve_prefix_hits_total" in rep
    # bucketed prefill accounts its padding waste
    assert rep["mx_decode_bucket_pad_tokens_total"]["value"] > 0


def test_engine_drain_finishes_running_rejects_new(net, ref_dec):
    """shutdown(drain=True): requests in slots finish completely (also
    mid-prefill ones), the never-admitted queue and new submits are
    rejected loudly."""
    e = serve.ServeEngine(net, max_slots=2, max_len=64, max_queue=8)
    prompts, _ = _mixed_requests(3, seed=7)
    h1 = e.submit(prompts[0], 8)
    h2 = e.submit(prompts[1], 8)
    h3 = e.submit(prompts[2], 8)           # stays queued: only 2 slots
    e.step()                               # admit h1/h2, first decode
    assert h3.state == "queued"
    e.shutdown(drain=True)
    assert h1.done and h2.done and h1.error is None and h2.error is None
    for p, h in [(prompts[0], h1), (prompts[1], h2)]:
        ref = ref_dec.generate(p[None, :], 8).asnumpy()[0]
        got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
        onp.testing.assert_array_equal(got, ref)
    with pytest.raises(EngineClosed):
        h3.result()
    with pytest.raises(EngineClosed):
        e.submit(prompts[0], 4)


@pytest.mark.slow
def test_bench_gpt_serve_contract():
    """The bench lands real numbers under the loud-failure contract:
    nonzero tokens/s and TTFT percentiles, occupancy from the registry
    (reduced trace; the committed extras run the full 32-request one)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)

    tok_s, p50, p99, occ = bench.bench_gpt_serve(
        requests=6, max_slots=3, prompt_max=24, new_max=16,
        mean_interarrival_s=0.01)
    assert tok_s > 0
    assert p99 >= p50 > 0
    assert 0 < occ <= 1


@pytest.mark.slow
def test_bench_gpt_serve_prefix_contract():
    """Reduced shared-prefix bench: reuse beats the cold path and the
    hit-rate/occupancy extras come back sane (the committed extras run
    the full workload)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)

    res = bench.bench_gpt_serve_prefix(requests=8, max_slots=2,
                                       prefix_len=96, tail_max=8,
                                       new_max=6)
    assert res["reuse_tokens_s"] > 0 and res["base_tokens_s"] > 0
    assert res["hit_rate"] > 0
    assert res["kv_bytes_per_slot"] > 0


# ---------------------------------------------------------------------------
# speculative decoding + per-layer pool layout (ISSUE 11)
# ---------------------------------------------------------------------------

def test_ngram_proposer_unit():
    """Host n-gram drafting: longest-suffix continuation lookup with a
    repeat-last fallback, always exactly k tokens."""
    from incubator_mxnet_tpu.models.decoding import NgramProposer

    p = NgramProposer(3, max_ngram=3)
    # the suffix [7, 8] occurred earlier, continued by [9, 1, 2]
    seq = onp.array([7, 8, 9, 1, 2, 7, 8], onp.int32)
    assert list(p.propose(seq)) == [9, 1, 2]
    # the suffix [5, 6] recurs with a full continuation window
    seq = onp.array([5, 6, 1, 5, 6], onp.int32)
    assert list(p.propose(seq)) == [1, 5, 6]
    # a short continuation pads with its own last token
    seq = onp.array([9, 5, 6, 5, 6], onp.int32)
    assert list(p.propose(seq)) == [5, 6, 6]
    # no suffix recurs: repeat the last token
    seq = onp.array([1, 2, 3], onp.int32)
    assert list(p.propose(seq)) == [3, 3, 3]
    with pytest.raises(ValueError):
        NgramProposer(0)


def test_spec_engine_validation_and_env_knobs(net, monkeypatch):
    """spec_k rides MXNET_SERVE_SPEC_K; sampling and an undersized or
    vocab-mismatched draft model fail loudly at construction."""
    from incubator_mxnet_tpu.serve.engine import SlotDecoder

    monkeypatch.setenv("MXNET_SERVE_SPEC_K", "2")
    s = SlotDecoder(net, max_slots=2, max_len=64)
    assert s.spec_k == 2 and s.draft_kind == "ngram"
    monkeypatch.delenv("MXNET_SERVE_SPEC_K")
    s = SlotDecoder(net, max_slots=2, max_len=64)
    assert s.spec_k == 0 and s.draft_kind == "off"
    with pytest.raises(ValueError, match="greedy"):
        SlotDecoder(net, max_slots=2, max_len=64, spec_k=3,
                    do_sample=True)
    with pytest.raises(ValueError, match="spec_k"):
        SlotDecoder(net, max_slots=2, max_len=64, spec_k=-1)
    small = gpt_tiny(vocab_size=VOCAB, max_length=32, dropout=0.0)
    small.initialize()
    with pytest.raises(ValueError, match="position table"):
        SlotDecoder(net, max_slots=2, max_len=64, spec_k=3, draft=small)
    other_vocab = gpt_tiny(vocab_size=31, max_length=64, dropout=0.0)
    other_vocab.initialize()
    with pytest.raises(ValueError, match="vocab"):
        SlotDecoder(net, max_slots=2, max_len=64, spec_k=3,
                    draft=other_vocab)


def test_spec_decode_parity_ngram_and_never_recompiles(net, ref_dec):
    """The spec acceptance gate: with the n-gram draft armed, every
    request's output is token-for-token identical to non-speculative
    greedy decode, program count stays flat in steady state, and the
    drafted/accepted counters move."""
    from incubator_mxnet_tpu.telemetry import registry

    e = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32,
                          spec_k=3, draft="ngram")
    try:
        drafted0 = registry.counter(
            "mx_serve_spec_drafted_tokens_total").value
        e.generate(_prompt(5, seed=9), 3)          # warm bucket + verify
        warm = e.xla_program_count()
        prompts, budgets = _mixed_requests(9, seed=1)
        handles = [e.submit(p, b) for p, b in zip(prompts, budgets)]
        e._drive_until(handles)
        for p, b, h in zip(prompts, budgets, handles):
            ref = ref_dec.generate(p[None, :], b).asnumpy()[0]
            got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
            onp.testing.assert_array_equal(got, ref)
        assert e.xla_program_count() == warm       # zero steady-state
        st = e.spec_stats()
        assert st["k"] == 3 and st["draft"] == "ngram"
        assert st["drafted"] > 0
        drafted = registry.counter(
            "mx_serve_spec_drafted_tokens_total").value - drafted0
        assert drafted == st["drafted"]
        # the per-model acceptance gauge is exported
        rep = registry.report()
        key = 'mx_serve_spec_accept_rate{model="serve"}'
        assert key in rep
        assert rep[key]["value"] == pytest.approx(st["accept_rate"])
    finally:
        e.shutdown(drain=False)


def test_spec_self_draft_parity_and_acceptance(net, ref_dec):
    """Drafting with the target model itself must accept ~everything
    (the draft pool tracks the committed prefix exactly) while output
    stays bit-identical — the canary for draft-pool KV holes."""
    e = serve.ServeEngine(net, max_slots=3, max_len=64, max_queue=32,
                          spec_k=3, draft=GPTDecoder(net))
    try:
        prompts = [_prompt(int(onp.random.RandomState(i).randint(4, 12)),
                           seed=50 + i) for i in range(6)]
        handles = [e.submit(p, 40) for p in prompts]
        e._drive_until(handles)
        for p, h in zip(prompts, handles):
            ref = ref_dec.generate(p[None, :], 40).asnumpy()[0]
            got = onp.concatenate([p, onp.asarray(h.result(), onp.int32)])
            onp.testing.assert_array_equal(got, ref)
        st = e.spec_stats()
        assert st["draft"] == "model"
        assert st["accept_rate"] > 0.9, st
    finally:
        e.shutdown(drain=False)


def test_spec_page_rollback_refcounts(net):
    """The reservation ledger under rejection pressure: after every
    step each decoding slot holds exactly the pages its committed
    position needs (rejected-suffix pages rolled back), reservations
    never exceed the free pool, and a drained engine returns every
    page."""
    e = serve.ServeEngine(net, max_slots=2, max_len=64, max_queue=32,
                          page_tokens=8, spec_k=4, draft="ngram")
    sched = e._sched
    alloc = sched.slots.allocator
    pt = sched.slots.page_tokens
    try:
        prompts, budgets = _mixed_requests(6, seed=3, budget_lo=10,
                                           budget_hi=24)
        handles = [e.submit(p, b) for p, b in zip(prompts, budgets)]
        while not all(h.done for h in handles):
            e.step()
            assert alloc.free_pages >= sched._spec_reserved_total()
            for s, req in enumerate(sched._in_slot):
                if req is None or not sched._active[s]:
                    continue
                # post-trim: pages cover the committed position exactly
                assert len(req.pages) == int(sched._pos[s]) // pt + 1
                assert req.spec_reserved >= 0
        assert sched._spec_reserved_total() == 0
    finally:
        e.shutdown(drain=False)
    assert alloc.used_pages == 0                   # cache cleared too


def test_per_layer_pool_ledger_decode_cost_flat(net):
    """Tentpole (a) evidence, asserted from XLA's own accounting: the
    decode program's temp allocation is a small constant — it does NOT
    scale with the pool as it grows 4x (the old stacked-pool layout
    re-materialized the whole pool per step) — and every per-layer
    pool leaf appears in the donation map (aliased in place)."""
    from incubator_mxnet_tpu.telemetry import compiles

    temps, pools, aliased = [], [], []
    compiles.enable()
    try:
        for n_pages in (12, 48):
            compiles.reset()
            e = serve.ServeEngine(net, max_slots=3, max_len=64,
                                  max_queue=8, n_pages=n_pages)
            try:
                e.generate(_prompt(5, seed=1), 3)
                mem = compiles.ledger("serve.decode")[-1]["memory"]
                assert mem is not None and mem["temp"]
                temps.append(mem["temp"])
                pools.append(e._sched.slots.cache_bytes)
                aliased.append(mem.get("aliased_params"))
            finally:
                e.shutdown(drain=False)
    finally:
        compiles.disable()
        compiles.reset()
    assert pools[1] >= 3.5 * pools[0]              # the pool really grew
    # decode scratch is a fraction of the pool it updates, and FLAT
    assert temps[0] < 0.5 * pools[0]
    assert temps[1] < 0.15 * pools[1]
    assert temps[1] <= 1.5 * temps[0], (temps, pools)
    # all 2L per-layer pool leaves alias an output (donation held)
    n_layers = 2                                   # gpt_tiny
    assert aliased[0] is not None
    assert len(aliased[0]) >= 2 * n_layers, aliased[0]
