"""Pod-scale sharded serving (ISSUE 15): `ServeLayout` partition rules,
`ShardedSlotDecoder` mesh parity, `ReplicaRouter` dispatch, and the
gateway's drain-free weight hot-swap.

Coverage layers, all on the test-wide 8-device forced-CPU mesh:

- host-only layout/rule tests (quick): every decoder param leaf matches
  exactly one partition rule, unmatched leaves raise instead of silently
  replicating, heavy matmuls and the KV pools land on the tp axis;
- router-logic tests against stub replicas (quick): least-loaded page
  scoring, prefix-affinity warm-set restriction, tenant stickiness,
  viability filtering;
- compiled-engine tests: greedy parity with the unsharded engine on a
  1-device mesh (bit-identical) and a tp mesh, the
  two-program-families / zero-steady-state-recompile invariant, a clean
  `shardcheck_report` (SC001/SC004/SC005/SC006) on the real layout, the
  2L-pool-leaves-aliased donation gate from the compile ledger, and the
  gateway hot-swap completing a replayed stream with zero failures.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, serve
from incubator_mxnet_tpu.models.gpt import gpt_tiny
from incubator_mxnet_tpu.serve.gateway import Gateway, ModelRegistry
from incubator_mxnet_tpu.serve.router import ReplicaRouter, replica_meshes
from incubator_mxnet_tpu.serve.scheduler import Scheduler
from incubator_mxnet_tpu.serve.sharded import (ServeLayout,
                                               ShardedSlotDecoder,
                                               parse_mesh_spec, serve_mesh)

VOCAB = 97
N_LAYERS = 2        # gpt_tiny


def _prompt(n, seed=0):
    return onp.random.RandomState(seed).randint(
        0, VOCAB, (n,)).astype(onp.int32)


@pytest.fixture(scope="module")
def net():
    mx.random.seed(11)
    m = gpt_tiny(vocab_size=VOCAB, max_length=64, dropout=0.0)
    m.initialize()
    r = onp.random.RandomState(42)
    for _name, p in m.collect_params().items():
        if p.shape and len(p.shape) >= 2:
            p.set_data(np.array(
                r.normal(0, 0.35, p.shape).astype("float32")))
    return m


def _mesh(tp):
    import jax

    return serve_mesh({"tp": tp}, devices=jax.devices()[:tp])


def _serve_tokens(slots, prompts, max_new=10):
    sched = Scheduler(slots, max_queue=16, seed=0)
    reqs = [sched.submit(p, max_new, temperature=1.0) for p in prompts]
    for _ in range(4000):
        sched.step()
        if all(r.done for r in reqs):
            break
    assert all(r.done for r in reqs)
    return [r.result() for r in reqs]


# ---------------------------------------------------------------------------
# layout rules — host-only (quick)
# ---------------------------------------------------------------------------

def test_every_param_leaf_matches_exactly_one_rule(net):
    import jax

    from incubator_mxnet_tpu.models.decoding import GPTDecoder
    from incubator_mxnet_tpu.serve.sharded import _path_str

    layout = ServeLayout(_mesh(1))
    params = GPTDecoder(net)._params
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    assert flat
    for path, _leaf in flat:
        p = _path_str(path)
        hits = [rx.pattern for rx, _ in layout._compiled if rx.search(p)]
        assert len(hits) == 1, (p, hits)
        layout.spec_for(p)      # resolves without error


def test_unmatched_leaf_raises_no_replicated_fallback():
    layout = ServeLayout(_mesh(1))
    with pytest.raises(ValueError, match="no partition rule"):
        layout.spec_for("layers/mystery_w")


def test_heavy_leaves_and_pools_land_on_tp():
    layout = ServeLayout(_mesh(2))
    for name in ("layers/qkv_w", "layers/proj_w", "layers/ffn1_w",
                 "layers/ffn2_w"):
        assert "tp" in tuple(layout.spec_for(name)), name
    # norms / embeddings are replicated EXPLICITLY (not a fallback)
    for name in ("layers/ln1_g", "embed", "pos", "lnf_g"):
        assert "tp" not in tuple(layout.spec_for(name)), name
    # pools shard the head axis; scale planes follow
    assert tuple(layout.pool_spec())[1] == "tp"
    assert tuple(layout.scale_spec())[1] == "tp"


def test_parse_mesh_spec_grammar():
    assert parse_mesh_spec(4) == {"tp": 4}
    assert parse_mesh_spec("4") == {"tp": 4}
    assert parse_mesh_spec("tp=2") == {"tp": 2}
    assert parse_mesh_spec("fsdp=2,tp=4") == {"fsdp": 2, "tp": 4}
    assert parse_mesh_spec("") == {"tp": 1}
    with pytest.raises(ValueError, match="mesh spec"):
        parse_mesh_spec("tp:4")


def test_replica_meshes_disjoint_slices():
    import jax

    meshes = replica_meshes("tp=2", 2, devices=jax.devices())
    assert len(meshes) == 2
    seen = [d for m in meshes for d in m.devices.flat]
    assert len(seen) == len(set(seen)) == 4
    with pytest.raises(ValueError, match="need"):
        replica_meshes("tp=4", 3, devices=jax.devices())


def test_divisibility_check_is_loud(net):
    with pytest.raises(ValueError, match="divisible"):
        ShardedSlotDecoder(net, mesh=serve_mesh({"tp": 3}),
                           max_slots=2, max_len=64, n_pages=16)


# ---------------------------------------------------------------------------
# router logic — stub replicas (quick)
# ---------------------------------------------------------------------------

class _StubCache:
    def __init__(self, warm):
        self._warm = warm

    def shared_tokens(self, prompt):
        return self._warm


class _StubRep:
    class _Alloc:
        def __init__(self, free, usable):
            self.free_pages = free
            self.usable_pages = usable

    class _Sched:
        def __init__(self, depth):
            self.queue_depth = depth

    class _Slots:
        pass

    def __init__(self, free=8, usable=8, depth=0, warm=None, label="r"):
        self.slots = self._Slots()
        self.slots.allocator = self._Alloc(free, usable)
        if warm is not None:
            self.slots.prefix_cache = _StubCache(warm)
        self.sched = self._Sched(depth)
        self.label = label


def test_router_least_loaded_picks_free_pages():
    r = ReplicaRouter(affinity="off")
    a = _StubRep(free=2, usable=8, label="a")
    b = _StubRep(free=7, usable=8, label="b")
    assert r.pick([a, b]) is b
    # a deep queue penalizes an otherwise-free replica
    c = _StubRep(free=8, usable=8, depth=8, label="c")
    assert r.pick([b, c]) is b
    # viability filter wins over score
    assert r.pick([a, b], viable=lambda rep: rep is a) is a
    assert r.pick([], viable=None) is None
    assert r.pick([a, b], viable=lambda rep: False) is None


def test_router_prefers_warm_prefix_replica():
    r = ReplicaRouter(affinity="prefix")
    cold = _StubRep(free=8, usable=8, warm=0, label="cold")
    warm = _StubRep(free=2, usable=8, warm=32, label="warm")
    # warm pages beat free pages
    assert r.pick([cold, warm], prompt=_prompt(40)) is warm
    # nothing warm anywhere -> pure least-loaded
    cold2 = _StubRep(free=5, usable=8, warm=0, label="cold2")
    assert r.pick([cold, cold2], prompt=_prompt(40)) is cold
    # a warm replica that fails viability is skipped, not waited on
    assert r.pick([cold, warm], prompt=_prompt(40),
                  viable=lambda rep: rep is cold) is cold


def test_router_tenant_affinity_stable_and_validated():
    r = ReplicaRouter(affinity="tenant")
    reps = [_StubRep(label=f"r{i}") for i in range(4)]
    picks = {r.pick(reps, tenant="alice").label for _ in range(5)}
    assert len(picks) == 1                      # stable across calls
    # preferred replica not viable -> least-loaded among the viable
    pref = r.pick(reps, tenant="alice")
    other = r.pick(reps, tenant="alice",
                   viable=lambda rep: rep is not pref)
    assert other is not pref
    with pytest.raises(ValueError, match="affinity"):
        ReplicaRouter(affinity="bogus")


# ---------------------------------------------------------------------------
# compiled engines — parity, program families, shardcheck, donation
# ---------------------------------------------------------------------------

def test_one_device_mesh_greedy_parity(net):
    prompts = [_prompt(7, seed=1), _prompt(11, seed=2)]
    base = serve.SlotDecoder(net, max_slots=2, max_len=64, n_pages=24)
    try:
        want = _serve_tokens(base, prompts)
    finally:
        base.release()
    sh = ShardedSlotDecoder(net, mesh=_mesh(1), max_slots=2, max_len=64,
                            n_pages=24)
    try:
        got = _serve_tokens(sh, prompts)
    finally:
        sh.release()
    assert got == want      # bit-identical greedy stream


def test_tp_mesh_parity_two_families_and_clean_shardcheck(net):
    prompts = [_prompt(7, seed=1), _prompt(11, seed=2)]
    base = serve.SlotDecoder(net, max_slots=2, max_len=64, n_pages=24)
    try:
        want = _serve_tokens(base, prompts)
    finally:
        base.release()

    from incubator_mxnet_tpu.telemetry import compiles

    compiles.enable()
    try:
        compiles.reset()
        sh = ShardedSlotDecoder(net, mesh=_mesh(2), max_slots=2,
                                max_len=64, n_pages=24)
        try:
            got = _serve_tokens(sh, prompts)
            assert got == want
            programs = sh.xla_program_count()
            # steady state: 3x more traffic, zero new programs
            _serve_tokens(sh, [_prompt(9, seed=s) for s in range(6)])
            assert sh.xla_program_count() == programs
            report = sh.shardcheck_report()
            for fam in ("prefill", "decode"):
                assert report[fam].findings == [], (
                    fam, [(f.rule, f.message) for f in report[fam].findings])
            # the TP pair's per-token collective is the all-reduce;
            # nothing re-materializes a sharded operand on the hot path
            assert "all-reduce" in report["decode"].collectives
            # XLA's own donation map: all 2L per-layer pool leaves alias
            mem = compiles.ledger("serve.decode")[-1]["memory"]
            aliased = mem.get("aliased_params")
            assert aliased is not None
            assert len(aliased) >= 2 * N_LAYERS, aliased
        finally:
            sh.release()
    finally:
        compiles.disable()
        compiles.reset()


def test_tp_mesh_int8_kv_runs_with_clean_shardcheck(net):
    sh = ShardedSlotDecoder(net, mesh=_mesh(2), max_slots=2, max_len=64,
                            n_pages=24, kv_dtype="int8")
    try:
        toks = _serve_tokens(sh, [_prompt(7, seed=1)])
        assert toks[0] and len(toks[0]) <= 10
        report = sh.shardcheck_report()
        for fam in ("prefill", "decode"):
            assert report[fam].findings == [], (
                fam, [(f.rule, f.message) for f in report[fam].findings])
    finally:
        sh.release()


def test_hbm_budget_gate_fires_sc006(net):
    sh = ShardedSlotDecoder(net, mesh=_mesh(2), max_slots=2, max_len=64,
                            n_pages=24, hbm_budget_gb=1e-6)
    try:
        report = sh.shardcheck_report()
        rules = {f.rule for f in report["decode"].findings}
        assert "SC006" in rules
    finally:
        sh.release()


# ---------------------------------------------------------------------------
# gateway: replica routing end-to-end + drain-free hot swap
# ---------------------------------------------------------------------------

def test_gateway_replicas_route_and_hot_swap_drain_free(net):
    reg = ModelRegistry(total_pages=96)
    reg.add("m", net, replicas=2, mesh="tp=2", max_slots=2, max_len=64)
    gw = Gateway(reg, seed=0)
    try:
        # phase 1: spread traffic across both replicas
        first = [gw.submit("m", _prompt(6, seed=s), 8) for s in range(6)]
        for _ in range(4000):
            gw.step()
            if all(r.done for r in first):
                break
        assert all(r.done for r in first)
        assert {r.replica for r in first} == {"m#0", "m#1"}

        # phase 2: swap weights mid-stream — one replica at a time,
        # zero failed requests, no drain
        inflight = [gw.submit("m", _prompt(6, seed=10 + s), 8)
                    for s in range(4)]
        gw.step()
        r = onp.random.RandomState(5)
        for _name, p in net.collect_params().items():
            if p.shape and len(p.shape) >= 2:
                p.set_data(np.array(
                    r.normal(0, 0.3, p.shape).astype("float32")))
        swapped = gw.hot_swap("m")
        assert swapped == {"m#0": True, "m#1": True}
        for _ in range(4000):
            gw.step()
            if all(q.done for q in inflight):
                break
        assert all(q.done for q in inflight)
        assert all(q.result() for q in inflight)    # no failures

        # a second swap with unchanged weights is a no-op per replica
        assert gw.hot_swap("m") == {"m#0": False, "m#1": False}
    finally:
        gw.shutdown()


def test_gateway_single_replica_backcompat(net):
    reg = ModelRegistry(total_pages=48)
    reg.add("s", net, max_slots=2, max_len=64)
    gw = Gateway(reg, seed=0)
    try:
        req = gw.submit("s", _prompt(6, seed=1), 6)
        for _ in range(2000):
            gw.step()
            if req.done:
                break
        assert req.done
        # single-replica label is the model name, and the pre-replica
        # metric series stay unlabeled (no {replica=} view emitted)
        assert gw._models["s"].replicas[0].label == "s"
        counts = gw.xla_program_counts()
        assert isinstance(counts["s"], int)
    finally:
        gw.shutdown()
