"""Estimator + expanded metrics tests (reference:
`tests/python/unittest/test_gluon_estimator.py`,
`test_gluon_event_handler.py`, `test_metric.py`)."""
import logging
import os

import numpy as onp
import pytest

from incubator_mxnet_tpu import gluon, np
from incubator_mxnet_tpu.gluon import metric
from incubator_mxnet_tpu.gluon.contrib.estimator import (
    CheckpointHandler, EarlyStoppingHandler, Estimator, EpochEnd,
    LoggingHandler, StoppingHandler)


def _make_data(n=256, d=4):
    X = np.random.uniform(size=(n, d))
    W = np.random.uniform(size=(d, 1))
    Y = X @ W
    ds = gluon.data.ArrayDataset(X, Y)
    return gluon.data.DataLoader(ds, batch_size=32), X, Y


def _make_est(net=None, lr=0.05):
    if net is None:
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(1))
        net.initialize()
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    return Estimator(net, loss=gluon.loss.L2Loss(), trainer=trainer,
                     train_metrics=metric.MSE())


def test_estimator_fit_learns():
    loader, _, _ = _make_data()
    est = _make_est()
    est.logger.setLevel(logging.ERROR)
    est.fit(loader, epochs=20)
    _, mse = est.train_metrics[0].get()
    assert mse < 0.01, mse


def test_estimator_evaluate():
    loader, _, _ = _make_data()
    est = _make_est()
    est.logger.setLevel(logging.ERROR)
    est.fit(loader, epochs=5)
    res = est.evaluate(loader)
    assert "validation mse" in res
    assert res["validation mse"] == pytest.approx(
        est.val_metrics[0].get()[1])


def test_estimator_max_batch_stops():
    loader, _, _ = _make_data()
    est = _make_est()
    est.logger.setLevel(logging.ERROR)
    seen = []

    class Counter(EpochEnd):
        def epoch_end(self, estimator, *a, **k):
            seen.append(1)

    est.fit(loader, batches=3, event_handlers=[Counter()])
    # 3 batches < 1 epoch: must stop before any epoch completes more than once
    assert len(seen) <= 1


def test_estimator_checkpoint(tmp_path):
    loader, _, _ = _make_data()
    est = _make_est()
    est.logger.setLevel(logging.ERROR)
    ckpt = CheckpointHandler(str(tmp_path), model_prefix="m", epoch_period=1)
    est.fit(loader, epochs=2, event_handlers=[ckpt])
    saved = os.listdir(tmp_path)
    assert any(f.endswith(".params") for f in saved)
    assert any(f.endswith(".states") for f in saved)


def test_estimator_early_stopping():
    loader, _, _ = _make_data()
    est = _make_est(lr=0.0)  # frozen → no improvement → stop after patience
    est.logger.setLevel(logging.ERROR)
    monitor = est.train_metrics[0]
    handler = EarlyStoppingHandler(monitor=monitor, patience=2, mode="min")
    est.fit(loader, epochs=50, event_handlers=[handler])
    assert handler.current_epoch < 50


def test_estimator_does_not_mutate_caller_metrics():
    m = metric.MSE()
    _make_est_with_metric(m)
    assert m.name == "mse"
    _make_est_with_metric(m)
    assert m.name == "mse"


def _make_est_with_metric(m):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(1))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    return Estimator(net, loss=gluon.loss.L2Loss(), trainer=trainer,
                     train_metrics=m)


def test_evaluate_fires_handlers():
    from incubator_mxnet_tpu.gluon.contrib.estimator import BatchEnd

    loader, _, _ = _make_data(n=64)
    est = _make_est()
    est.logger.setLevel(logging.ERROR)
    calls = []

    class H(BatchEnd):
        def batch_end(self, estimator, *a, **k):
            calls.append(1)

    est.evaluate(loader, event_handlers=[H()])
    assert len(calls) == 2  # 64 samples / batch 32


# -- metrics ------------------------------------------------------------------

def test_f1_micro_macro():
    label = onp.array([1, 0, 1, 1, 0])
    pred = onp.array([0.8, 0.2, 0.6, 0.3, 0.7])
    for average in ("micro", "macro"):
        m = metric.F1(average=average)
        m.update(label, pred)
        tp, fp, fn = 2, 1, 1
        prec, rec = tp / (tp + fp), tp / (tp + fn)
        want = 2 * prec * rec / (prec + rec)
        assert m.get()[1] == pytest.approx(want)
    # macro averages per-update scores; micro aggregates counts
    m_micro, m_macro = metric.F1(average="micro"), metric.F1(average="macro")
    l2, p2 = onp.array([1, 1]), onp.array([0.9, 0.9])
    for m in (m_micro, m_macro):
        m.update(label, pred)
        m.update(l2, p2)
    assert m_micro.get()[1] != pytest.approx(m_macro.get()[1])


def test_fbeta():
    label = onp.array([1, 0, 1, 1])
    pred = onp.array([0.9, 0.8, 0.7, 0.1])
    m = metric.Fbeta(beta=2, average="micro")
    m.update(label, pred)
    tp, fp, fn = 2, 1, 1
    prec, rec = tp / (tp + fp), tp / (tp + fn)
    want = 5 * prec * rec / (4 * prec + rec)
    assert m.get()[1] == pytest.approx(want)


def test_binary_accuracy():
    m = metric.BinaryAccuracy(threshold=0.6)
    m.update(onp.array([1, 0, 1, 0]), onp.array([0.7, 0.2, 0.5, 0.8]))
    assert m.get()[1] == pytest.approx(0.5)


def test_pcc_matches_mcc_binary():
    rng = onp.random.RandomState(0)
    label = rng.randint(0, 2, 100)
    pred = (label ^ (rng.uniform(size=100) > 0.8)).astype("int32")
    pcc, mcc = metric.PCC(), metric.MCC()
    pcc.update(label, pred)
    mcc.update(label, pred.astype("float32"))
    assert pcc.get()[1] == pytest.approx(mcc.get()[1], abs=1e-6)


def test_pcc_multiclass():
    label = onp.array([0, 1, 2, 2, 1, 0])
    pred = onp.eye(3)[[0, 1, 2, 2, 1, 0]]
    m = metric.PCC()
    m.update(label, pred)
    assert m.get()[1] == pytest.approx(1.0)


def test_mean_pairwise_distance():
    m = metric.MeanPairwiseDistance()
    pred = onp.array([[3.0, 4.0], [0.0, 0.0]])
    label = onp.array([[0.0, 0.0], [0.0, 0.0]])
    m.update(label, pred)
    assert m.get()[1] == pytest.approx(2.5)  # (5 + 0) / 2


def test_mean_cosine_similarity():
    m = metric.MeanCosineSimilarity()
    pred = onp.array([[1.0, 0.0], [0.0, 2.0]])
    label = onp.array([[2.0, 0.0], [0.0, 1.0]])
    m.update(label, pred)
    assert m.get()[1] == pytest.approx(1.0)


def test_np_custom_metric():
    def zero_one(label, pred):
        return float((label != (pred > 0.5)).mean())

    m = metric.np(zero_one)
    m.update(onp.array([1, 0]), onp.array([0.9, 0.8]))
    assert m.get()[1] == pytest.approx(0.5)
    assert "zero_one" in m.get()[0]


def test_create_by_name():
    assert isinstance(metric.create("f1"), metric.F1)
    assert isinstance(metric.create("pcc"), metric.PCC)
    assert isinstance(metric.create("binaryaccuracy"), metric.BinaryAccuracy)
