"""Higher-order gradient tests (reference model:
tests/python/unittest/test_higher_order_grad.py — record, take
autograd.grad(..., create_graph=True), then backward the grad)."""
import numpy as onp

from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def second_order(fn, x0):
    """d²/dx² of sum(fn(x)) via grad-of-grad, reference autograd.py:272
    create_graph pattern."""
    x = NDArray(x0)
    x.attach_grad()
    with autograd.record():
        y = fn(x)
        (dx,) = autograd.grad([y.sum()], [x], create_graph=True)
        s = dx.sum()
    s.backward()
    return A(x.grad)


def test_second_order_sin():
    x0 = onp.linspace(-1.0, 1.0, 7).astype(onp.float32)
    onp.testing.assert_allclose(second_order(mnp.sin, x0), -onp.sin(x0),
                                rtol=1e-4, atol=1e-5)


def test_second_order_polynomial():
    x0 = onp.array([0.5, 1.0, 2.0], onp.float32)
    onp.testing.assert_allclose(second_order(lambda x: x ** 3, x0),
                                6 * x0, rtol=1e-4)


def test_second_order_log():
    x0 = onp.array([0.3, 0.7, 1.5], onp.float32)
    onp.testing.assert_allclose(second_order(mnp.log, x0), -1.0 / x0 ** 2,
                                rtol=1e-4)


def test_second_order_exp():
    x0 = onp.array([0.3, 0.7, 1.5], onp.float32)
    onp.testing.assert_allclose(second_order(mnp.exp, x0), onp.exp(x0),
                                rtol=1e-4)


def test_second_order_sigmoid():
    x0 = onp.array([-1.0, 0.0, 1.0], onp.float32)

    def sigmoid(x):
        return 1.0 / (1.0 + mnp.exp(-x))

    s = 1.0 / (1.0 + onp.exp(-x0))
    want = s * (1 - s) * (1 - 2 * s)
    onp.testing.assert_allclose(second_order(sigmoid, x0), want,
                                rtol=1e-3, atol=1e-5)


def test_first_order_grad_values_with_create_graph():
    x0 = onp.array([1.0, 2.0], onp.float32)
    x = NDArray(x0)
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        (dx,) = autograd.grad([y], [x], create_graph=True)
    onp.testing.assert_allclose(A(dx), 4 * x0 ** 3, rtol=1e-5)


def test_grad_grad_matmul():
    w0 = onp.array([[1.0, 2.0], [3.0, 4.0]], onp.float32)
    w = NDArray(w0)
    w.attach_grad()
    with autograd.record():
        y = mnp.dot(w, w).sum()
        (dw,) = autograd.grad([y], [w], create_graph=True)
        s = (dw * dw).sum()
    s.backward()
    # finite-difference check of d/dw sum(grad^2)
    eps = 1e-3

    def g_of(wv):
        ww = NDArray(wv)
        ww.attach_grad()
        with autograd.record():
            yy = mnp.dot(ww, ww).sum()
            (d,) = autograd.grad([yy], [ww], create_graph=False)
        return A(d)

    num = onp.zeros_like(w0)
    for i in range(2):
        for j in range(2):
            wp = w0.copy()
            wp[i, j] += eps
            wm = w0.copy()
            wm[i, j] -= eps
            num[i, j] = ((g_of(wp) ** 2).sum() - (g_of(wm) ** 2).sum()) \
                / (2 * eps)
    onp.testing.assert_allclose(A(w.grad), num, rtol=1e-2, atol=1e-2)
