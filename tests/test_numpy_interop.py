"""NumPy dispatch protocol on NDArray (reference:
`python/mxnet/numpy_dispatch_protocol.py` — NEP-18/NEP-13): plain-numpy
functions called ON framework arrays dispatch into the framework and
return NDArrays."""
import numpy as onp

from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.ndarray.ndarray import NDArray


def _arr(shape, seed=0):
    return np.array(onp.random.RandomState(seed)
                    .uniform(-1, 1, shape).astype("float32"))


def test_array_function_mean_stack_where():
    x = _arr((4, 5))
    y = _arr((4, 5), seed=1)

    m = onp.mean(x, axis=1)
    assert isinstance(m, NDArray)
    onp.testing.assert_allclose(m.asnumpy(), x.asnumpy().mean(1), rtol=1e-6)

    s = onp.stack([x, y])
    assert isinstance(s, NDArray)
    assert s.shape == (2, 4, 5)

    c = onp.where(x.asnumpy() > 0)  # plain numpy stays plain numpy
    w = onp.where(x > 0, x, y)
    assert isinstance(w, NDArray)
    onp.testing.assert_allclose(
        w.asnumpy(), onp.where(x.asnumpy() > 0, x.asnumpy(), y.asnumpy()))
    del c


def test_array_ufunc_binary_and_unary():
    x = _arr((3, 4))
    y = _arr((3, 4), seed=2)
    z = onp.add(x, y)
    assert isinstance(z, NDArray)
    onp.testing.assert_allclose(z.asnumpy(), x.asnumpy() + y.asnumpy(),
                                rtol=1e-6)
    e = onp.exp(x)
    assert isinstance(e, NDArray)
    onp.testing.assert_allclose(e.asnumpy(), onp.exp(x.asnumpy()), rtol=1e-6)
    # mixed NDArray + numpy operand: still dispatches to the framework
    z2 = onp.multiply(x, y.asnumpy())
    assert isinstance(z2, NDArray)


def test_array_coercion():
    x = _arr((2, 3))
    a = onp.asarray(x)
    assert type(a) is onp.ndarray
    onp.testing.assert_array_equal(a, x.asnumpy())
    a64 = onp.asarray(x, dtype="float64")
    assert a64.dtype == onp.float64


def test_unsupported_protocol_paths_coerce_to_host():
    x = _arr((2, 2))
    # calls the framework can't dispatch (masked where=, out=, ufunc
    # methods) degrade to HOST numpy via coercion — the pre-protocol
    # behavior — returning plain numpy arrays
    out = onp.add(x, x, where=onp.array([[True, False], [True, True]]))
    assert type(out) is onp.ndarray
    onp.testing.assert_allclose(out[0, 0], 2 * x.asnumpy()[0, 0])
    red = onp.add.reduce(x)            # ufunc method
    assert type(red) is onp.ndarray
    onp.testing.assert_allclose(red, x.asnumpy().sum(0), rtol=1e-6)
    buf = onp.zeros((2, 2), "float32")
    onp.multiply(x, 2.0, out=buf)      # out= kwarg
    onp.testing.assert_allclose(buf, 2 * x.asnumpy(), rtol=1e-6)


def test_undispatched_numpy_functions_coerce():
    """Functions absent from the framework namespace (np.save etc.) keep
    the pre-protocol coercion behavior instead of raising under NEP-18."""
    import os
    import tempfile

    x = _arr((3, 4))
    f = tempfile.mktemp(suffix=".npy")
    try:
        onp.save(f, x)
        onp.testing.assert_allclose(onp.load(f), x.asnumpy())
    finally:
        if os.path.exists(f):
            os.remove(f)
