"""Dtype, device, and util-surface depth: casts across every supported
dtype, device API parity, util switches, DLPack/numpy interop edges
(reference: `tests/python/unittest/test_ndarray.py` dtype blocks +
`test_utils`/device tests)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np
from incubator_mxnet_tpu.device import Device, cpu, current_device

RNG = onp.random.RandomState(61)

FLOATS = ["float16", "float32", "bfloat16"]
INTS = ["int8", "int16", "int32", "uint8"]


def _a(*shape):
    return np.array(RNG.uniform(-2, 2, shape).astype("float32"))


# -- casts -------------------------------------------------------------------

def test_cast_f32_to_each_float():
    a = _a(3, 3)
    for dt in FLOATS:
        b = a.astype(dt)
        assert dt in str(b.dtype)
        onp.testing.assert_allclose(b.astype("float32").asnumpy(),
                                    a.asnumpy(), rtol=2e-2, atol=2e-2)


def test_cast_f32_to_each_int_truncates():
    a = np.array(onp.array([1.9, -1.9, 100.4], "float32"))
    for dt in ("int8", "int16", "int32"):
        b = a.astype(dt).asnumpy()
        onp.testing.assert_array_equal(b, [1, -1, 100])


def test_cast_int_to_float_exact():
    a = np.array(onp.array([1, -7, 120], "int32"))
    for dt in ("float16", "float32"):
        onp.testing.assert_array_equal(a.astype(dt).asnumpy(),
                                       [1.0, -7.0, 120.0])


def test_cast_roundtrip_uint8():
    a = np.array(onp.array([0, 255, 128], "uint8"))
    b = a.astype("float32").astype("uint8")
    onp.testing.assert_array_equal(b.asnumpy(), [0, 255, 128])


def test_bool_array_dtype():
    a = np.array(onp.array([True, False]))
    assert "bool" in str(a.dtype)
    assert int(a.sum().asnumpy()) == 1


def test_dtype_preserved_through_arithmetic():
    for dt in ("float16", "float32"):
        a = _a(2, 2).astype(dt)
        assert dt in str((a + a).dtype)
        assert dt in str((a * 2).dtype)


def test_arange_dtypes():
    for dt in ("int32", "float32"):
        out = np.arange(5, dtype=dt)
        assert dt in str(out.dtype)


def test_zeros_ones_dtypes():
    for dt in FLOATS + ["int32"]:
        assert dt in str(np.zeros((2,), dtype=dt).dtype)
        assert dt in str(np.ones((2,), dtype=dt).dtype)


def test_float64_downcasts_to_float32():
    # jax default config: f64 inputs land as f32 (documented divergence
    # from the reference's true float64 support)
    a = np.array(onp.ones((2,), "float64"))
    assert "float32" in str(a.dtype)


# -- device API --------------------------------------------------------------

def test_cpu_device_constructor():
    d = cpu()
    assert d.device_type in ("cpu", "tpu")  # platform default may map


def test_device_equality_and_repr():
    assert Device("cpu", 0) == Device("cpu", 0)
    assert "cpu" in repr(Device("cpu", 0))


def test_current_device_exists():
    assert current_device() is not None


def test_array_device_attribute():
    a = _a(2)
    assert a.device is not None


def test_as_in_context_noop_single_device():
    a = _a(2, 2)
    b = a.as_in_context(a.context)
    onp.testing.assert_array_equal(b.asnumpy(), a.asnumpy())


def test_gpu_memory_info_shape():
    from incubator_mxnet_tpu import device as device_mod

    if not hasattr(device_mod, "gpu_memory_info"):
        pytest.skip("gpu_memory_info not exposed")
    free, total = device_mod.gpu_memory_info(0)
    assert total >= free >= 0


# -- util switches -----------------------------------------------------------

def test_np_shape_scope():
    from incubator_mxnet_tpu import util

    assert util.is_np_shape()          # always-on in the TPU build
    with util.np_shape(True):
        assert util.is_np_shape()


def test_np_array_scope():
    from incubator_mxnet_tpu import util

    assert util.is_np_array()
    util.set_np()
    assert util.is_np_array()


def test_getenv_setenv_roundtrip():
    from incubator_mxnet_tpu import util

    if not hasattr(util, "getenv"):
        pytest.skip("env helpers not exposed")
    util.setenv("MXNET_TEST_ENV_X", "1")
    assert util.getenv("MXNET_TEST_ENV_X") == "1"


# -- interop edges -----------------------------------------------------------

def test_numpy_protocol_ufunc():
    a = _a(2, 3)
    out = onp.exp(a)               # __array_ufunc__ path
    got = out.asnumpy() if hasattr(out, "asnumpy") else onp.asarray(out)
    onp.testing.assert_allclose(got, onp.exp(a.asnumpy()), rtol=1e-5)


def test_numpy_protocol_function():
    a = _a(2, 3)
    out = onp.concatenate([a, a])  # __array_function__ path
    got = out.asnumpy() if hasattr(out, "asnumpy") else onp.asarray(out)
    assert got.shape == (4, 3)


def test_dlpack_roundtrip():
    a = _a(3, 4)
    assert hasattr(a, "__dlpack__") and hasattr(a, "__dlpack_device__")
    import jax.numpy as jnp

    back = jnp.from_dlpack(a)      # protocol-object form (new-style)
    onp.testing.assert_allclose(onp.asarray(back), a.asnumpy(),
                                rtol=1e-6)


def test_asnumpy_never_aliases_device_value():
    a = _a(4)
    n = a.asnumpy()
    try:
        n[0] = 999.0               # either read-only (zero-copy view)...
    except ValueError:
        return
    assert float(a.asnumpy()[0]) != 999.0   # ...or a true copy


def test_tolist():
    a = np.array(onp.array([[1.0, 2.0]], "float32"))
    assert a.tolist() == [[1.0, 2.0]]


def test_len_and_iter():
    a = _a(3, 2)
    assert len(a) == 3
    rows = list(a)
    assert len(rows) == 3 and rows[0].shape == (2,)


def test_bool_of_scalar():
    assert bool(np.array(onp.array(1.0, "float32")))
    assert not bool(np.array(onp.array(0.0, "float32")))


def test_int_float_conversion():
    a = np.array(onp.array(2.7, "float32"))
    assert float(a) == pytest.approx(2.7, rel=1e-6)
    assert int(np.array(onp.array(5, "int32"))) == 5


def test_hashable_shapes_api():
    a = _a(2, 3)
    assert a.ndim == 2
    assert a.size == 6
    assert a.shape == (2, 3)
    assert a.T.shape == (3, 2)