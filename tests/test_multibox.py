"""SSD multibox op tests (reference model:
tests/python/unittest/test_contrib_operator.py multibox sections)."""
import numpy as onp

from incubator_mxnet_tpu import np as mnp
from incubator_mxnet_tpu import numpy_extension as npx


def A(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else onp.asarray(x)


def test_multibox_prior_shapes_and_layout():
    x = mnp.zeros((1, 8, 4, 4))
    anchors = npx.multibox_prior(x, sizes=[0.5, 0.25], ratios=[1, 2, 0.5])
    # A = 2 + 3 - 1 = 4 anchors per cell
    assert anchors.shape == (1, 4 * 4 * 4, 4)
    a = A(anchors)[0]
    # first anchor of the first cell: centered at (0.5/4, 0.5/4), size 0.5
    cx, cy = 0.5 / 4, 0.5 / 4
    onp.testing.assert_allclose(a[0], [cx - 0.25, cy - 0.25,
                                       cx + 0.25, cy + 0.25], atol=1e-6)
    # reference layout: sizes first (at ratios[0]) then ratios[1:]
    r2 = 2 ** 0.5
    onp.testing.assert_allclose(a[2], [cx - 0.25 * r2, cy - 0.25 / r2,
                                       cx + 0.25 * r2, cy + 0.25 / r2],
                                atol=1e-5)


def test_multibox_prior_clip():
    x = mnp.zeros((1, 1, 2, 2))
    anchors = A(npx.multibox_prior(x, sizes=[1.5], clip=True))
    assert anchors.min() >= 0.0 and anchors.max() <= 1.0


def test_multibox_target_perfect_match():
    x = mnp.zeros((1, 1, 1, 1))
    anchors = npx.multibox_prior(x, sizes=[1.0])  # one anchor ~ whole image
    a = A(anchors)[0, 0]
    label = mnp.array(onp.array(
        [[[0.0, a[0], a[1], a[2], a[3]],
          [-1.0, 0, 0, 0, 0]]], onp.float32))  # one gt + padding
    cls_pred = mnp.zeros((1, 2, 1))
    loc_t, loc_m, cls_t = npx.multibox_target(anchors, label, cls_pred)
    assert cls_t.shape == (1, 1)
    assert float(A(cls_t)[0, 0]) == 1.0          # class 0 → target 1
    onp.testing.assert_allclose(A(loc_t)[0], onp.zeros(4), atol=1e-5)
    onp.testing.assert_allclose(A(loc_m)[0], onp.ones(4))


def test_multibox_target_no_gt_is_all_background():
    x = mnp.zeros((1, 1, 2, 2))
    anchors = npx.multibox_prior(x, sizes=[0.5])
    label = mnp.array(onp.full((1, 2, 5), -1.0, onp.float32))
    cls_pred = mnp.zeros((1, 3, 4))
    loc_t, loc_m, cls_t = npx.multibox_target(anchors, label, cls_pred)
    assert (A(cls_t) == 0).all()
    assert (A(loc_m) == 0).all()


def test_multibox_target_force_match_low_iou():
    """Every valid gt claims its best anchor even below the threshold."""
    x = mnp.zeros((1, 1, 2, 2))
    anchors = npx.multibox_prior(x, sizes=[0.2])
    # tiny gt box far from any anchor's 0.5-IoU reach, near cell (0,0)
    label = mnp.array(onp.array(
        [[[1.0, 0.0, 0.0, 0.1, 0.1]]], onp.float32))
    cls_pred = mnp.zeros((1, 3, 4))
    _, _, cls_t = npx.multibox_target(anchors, label, cls_pred,
                                      overlap_threshold=0.9)
    assert (A(cls_t) == 2.0).sum() == 1  # exactly the forced match


def test_multibox_target_padding_does_not_clobber_force_match():
    """Padding rows (cls=-1) must not cancel a valid gt's forced anchor."""
    x = mnp.zeros((1, 1, 2, 1))
    anchors = npx.multibox_prior(x, sizes=[0.2])  # 2 anchors
    label = mnp.array(onp.array(
        [[[1.0, 0.0, 0.0, 0.12, 0.12],       # low-IoU gt → forced match
          [-1.0, 0.0, 0.0, 0.0, 0.0],        # padding
          [-1.0, 0.0, 0.0, 0.0, 0.0]]], onp.float32))
    cls_pred = mnp.zeros((1, 3, 2))
    _, _, cls_t = npx.multibox_target(anchors, label, cls_pred,
                                      overlap_threshold=0.95)
    assert (A(cls_t) == 2.0).sum() == 1


def test_multibox_target_negative_mining():
    x = mnp.zeros((1, 1, 4, 4))
    anchors = npx.multibox_prior(x, sizes=[0.3])   # 16 anchors
    n = anchors.shape[1]
    a0 = A(anchors)[0, 0]
    label = mnp.array(onp.array(
        [[[0.0, a0[0], a0[1], a0[2], a0[3]]]], onp.float32))
    # confidence ranking: anchors 1..3 are "hard" negatives
    pred = onp.zeros((1, 3, n), onp.float32)
    pred[0, 1, 1:4] = 0.9
    _, _, cls_t = npx.multibox_target(
        anchors, label, mnp.array(pred), negative_mining_ratio=3.0,
        ignore_label=-1.0)
    c = A(cls_t)[0]
    assert c[0] == 1.0                       # the positive
    assert (c == 0.0).sum() == 3             # 3 kept negatives (ratio 3×1)
    assert (c == -1.0).sum() == n - 4        # rest ignored


def test_multibox_target_two_gts_same_best_anchor():
    """Round-2 assignment: the losing gt gets its next-best anchor."""
    x = mnp.zeros((1, 1, 2, 1))
    anchors = npx.multibox_prior(x, sizes=[0.2])  # 2 anchors
    # both gts overlap anchor 0 best; second round must place the loser
    label = mnp.array(onp.array(
        [[[0.0, 0.0, 0.05, 0.12, 0.17],
          [1.0, 0.0, 0.08, 0.12, 0.20]]], onp.float32))
    cls_pred = mnp.zeros((1, 3, 2))
    _, _, cls_t = npx.multibox_target(anchors, label, cls_pred,
                                      overlap_threshold=0.95)
    c = A(cls_t)[0]
    assert (c > 0).sum() == 2  # both gts matched to distinct anchors


def test_multibox_detection_decodes_and_nms():
    x = mnp.zeros((1, 1, 2, 2))
    anchors = npx.multibox_prior(x, sizes=[0.4])          # (1, 4, 4)
    n = 4
    cls_prob = onp.zeros((1, 3, n), onp.float32)
    cls_prob[0, 0] = 0.1                                   # background
    cls_prob[0, 1] = [0.8, 0.7, 0.05, 0.05]                # class 0 strong
    cls_prob[0, 2] = 0.05
    loc_pred = onp.zeros((1, n * 4), onp.float32)          # no offset
    out = npx.multibox_detection(mnp.array(cls_prob), mnp.array(loc_pred),
                                 anchors, nms_threshold=0.9)
    o = A(out)[0]
    assert o.shape == (n, 6)
    kept = o[o[:, 0] >= 0]
    assert len(kept) >= 1
    assert kept[0, 0] == 0.0          # class id (background removed)
    assert abs(kept[0, 1] - 0.8) < 1e-5
    # decoded box equals the anchor (zero deltas)
    a = A(anchors)[0]
    onp.testing.assert_allclose(kept[0, 2:6], a[0], atol=1e-5)


def test_multibox_detection_threshold_filters():
    x = mnp.zeros((1, 1, 1, 1))
    anchors = npx.multibox_prior(x, sizes=[0.5])
    cls_prob = onp.array([[[0.9], [0.1]]], onp.float32)  # bg wins
    loc_pred = onp.zeros((1, 4), onp.float32)
    out = A(npx.multibox_detection(mnp.array(cls_prob),
                                   mnp.array(loc_pred), anchors,
                                   threshold=0.5))
    assert (out[0, :, 0] == -1).all()  # nothing above threshold
