"""INT8 quantization: calibration algorithms, quantized op numerics, and
end-to-end accuracy preservation (reference:
`tests/python/quantization/test_quantization.py`, accuracy discipline from
`example/quantization/README.md` — ≤0.5% top-1 drop)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np
from incubator_mxnet_tpu.contrib import quantization as q


def test_entropy_threshold_clips_outliers():
    """A gaussian bulk with a far outlier: entropy calibration should pick
    a threshold well below the outlier; naive picks the outlier."""
    rng = onp.random.RandomState(0)
    x = onp.abs(rng.randn(100000)).astype("float32")
    x[0] = 50.0  # outlier
    hist, edges = onp.histogram(onp.abs(x), bins=2048, range=(0, 50.0))
    t = q.optimal_threshold_entropy(hist, edges)
    assert t < 25.0, t
    assert t > 1.0, t


def test_quantized_dense_matches_fp32():
    rng = onp.random.RandomState(1)
    dense = gluon.nn.Dense(32, in_units=16)
    dense.initialize()
    x = np.array(rng.uniform(-2, 2, (8, 16)).astype("float32"))
    ref = dense(x).asnumpy()
    qd = q.QuantizedDense(dense, threshold=2.0)
    out = qd(x).asnumpy()
    # int8 quantization error bound: ~1% relative on well-scaled data
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.03


def test_quantized_conv_matches_fp32():
    rng = onp.random.RandomState(2)
    conv = gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=4)
    conv.initialize()
    x = np.array(rng.uniform(-1, 1, (2, 4, 12, 12)).astype("float32"))
    ref = conv(x).asnumpy()
    qc = q.QuantizedConv2D(conv, threshold=1.0)
    out = qc(x).asnumpy()
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.03


def _make_toy_problem(n=512, seed=0):
    """Linearly-separable-ish 4-class problem through a small conv net."""
    rng = onp.random.RandomState(seed)
    X = rng.uniform(-1, 1, (n, 3, 8, 8)).astype("float32")
    # class = argmax of 4 fixed random projections -> learnable
    W = rng.randn(4, 3 * 8 * 8).astype("float32")
    Y = (X.reshape(n, -1) @ W.T).argmax(1).astype("int32")
    return X, Y


def _accuracy(net, X, Y, bs=64):
    correct = 0
    for i in range(0, len(X), bs):
        out = net(np.array(X[i:i + bs]))
        correct += int((out.asnumpy().argmax(1) == Y[i:i + bs]).sum())
    return correct / len(X)


def test_quantize_net_end_to_end_accuracy():
    """Train fp32 -> quantize (entropy calib) -> accuracy drop must stay
    within the reference's discipline (≤0.5% on ImageNet-scale calib; a
    4-batch toy calibration is noisier, so ≤2% here)."""
    mx.random.seed(7)
    X, Y = _make_toy_problem()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, in_channels=3,
                            activation="relu"),
            gluon.nn.Conv2D(16, 3, padding=1, in_channels=16,
                            activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(12):
        for i in range(0, len(X), 64):
            xb, yb = np.array(X[i:i + 64]), np.array(Y[i:i + 64])
            with autograd.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
    acc_fp32 = _accuracy(net, X, Y)
    assert acc_fp32 > 0.8, f"fp32 net failed to train: {acc_fp32}"
    ref_out = net(np.array(X[:8])).asnumpy()

    calib = [np.array(X[i:i + 64]) for i in range(0, 256, 64)]
    q.quantize_net(net, calib_data=calib, calib_mode="entropy",
                   num_calib_batches=4)
    # every Dense/Conv must have been swapped — in _children AND in the
    # Sequential._layers list that forward() actually iterates
    assert all(type(c) in (q.QuantizedConv2D, q.QuantizedDense)
               for c in net._children.values())
    assert all(type(c) in (q.QuantizedConv2D, q.QuantizedDense)
               for c in net._layers)
    # and they must actually execute: int8 output differs from fp32
    assert not onp.array_equal(net(np.array(X[:8])).asnumpy(), ref_out)
    acc_int8 = _accuracy(net, X, Y)
    assert acc_fp32 - acc_int8 <= 0.02, (acc_fp32, acc_int8)


def test_quantize_hybridized_net_and_save_load(tmp_path):
    """Quantizing an already-hybridized (and traced) net must re-trace the
    quantized graph, and the quantized net must round-trip through
    save_parameters/load_parameters (weights live in Constant params)."""
    rng = onp.random.RandomState(3)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    x = np.array(rng.uniform(-1, 1, (4, 8)).astype("float32"))
    ref = net(x).asnumpy()           # builds the fp32 cached graph
    q.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = net(x).asnumpy()           # must NOT replay the stale fp32 graph
    assert not onp.array_equal(out, ref)
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.05

    f = str(tmp_path / "qnet.params")
    net.save_parameters(f)
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
             gluon.nn.Dense(4, in_units=16))
    net2.initialize()
    q.quantize_net(net2, calib_mode="none")   # same structure, wrong params
    net2.load_parameters(f)
    assert_close = onp.testing.assert_allclose
    assert_close(net2(x).asnumpy(), out, rtol=1e-5, atol=1e-5)


def test_quantize_net_exclude_and_naive():
    X, _ = _make_toy_problem(64)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=192, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    ref = net(np.array(X[:4].reshape(4, -1))).asnumpy()
    calib = [np.array(X[:32].reshape(32, -1))]
    q.quantize_net(net, calib_data=calib, calib_mode="naive",
                   exclude_layers_match=[r"^1$"])
    assert type(net._children["0"]) is q.QuantizedDense
    assert type(net._children["1"]) is gluon.nn.Dense  # excluded stays fp32
    # the swapped layer must actually execute: output differs from fp32
    # but stays within int8 error
    out = net(np.array(X[:4].reshape(4, -1))).asnumpy()
    assert not onp.array_equal(out, ref)
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.05


def test_quantize_requires_calib_data():
    # a net with no quantizable layers is a no-op, not an error
    q.quantize_net(gluon.nn.HybridSequential(), calib_mode="entropy")
    # but a net WITH layers must demand calibration data
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(4, in_units=8))
    net2.initialize()
    with pytest.raises(ValueError):
        q.quantize_net(net2, calib_mode="entropy")


def test_fold_conv_bn_matches_fp32():
    """Conv→BN folding (fold_conv_bn): the folded net must reproduce the
    conv+BN inference output exactly (affine algebra), with the BN replaced
    by Identity; parallel-branch declarations outside HybridSequential must
    NOT be folded."""
    rng = onp.random.RandomState(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4, use_bias=False),
            gluon.nn.BatchNorm(in_channels=8),
            gluon.nn.Activation("relu"),
            gluon.nn.Conv2D(8, 3, padding=1, in_channels=8),
            gluon.nn.BatchNorm(in_channels=8))
    net.initialize()
    x = np.array(rng.uniform(-1, 1, (2, 4, 8, 8)).astype("float32"))
    net(x)
    # give the running stats / affine params nontrivial values
    for name, p in net.collect_params().items():
        if "running_mean" in name or "beta" in name:
            p.set_data(np.array(rng.uniform(-0.5, 0.5,
                                            p.shape).astype("float32")))
        if "running_var" in name or "gamma" in name:
            p.set_data(np.array(rng.uniform(0.5, 2.0,
                                            p.shape).astype("float32")))
    ref = net(x).asnumpy()
    n = q.fold_conv_bn(net)
    assert n == 2
    assert type(net._children["1"]) is gluon.nn.Identity
    assert type(net._children["4"]) is gluon.nn.Identity
    out = net(x).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    # parallel branches declared adjacently in a NON-sequential block: no fold
    class Branchy(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.conv = gluon.nn.Conv2D(4, 1, in_channels=4)
            self.bn = gluon.nn.BatchNorm(in_channels=4)  # separate branch!

        def forward(self, x):
            return self.conv(x) + self.bn(x)

    b = Branchy()
    b.initialize()
    b(x)
    assert q.fold_conv_bn(b) == 0


def test_fold_conv_bn_skips_fused_activation():
    """Conv2D(activation='relu') -> BN must NOT fold: the relu sits between
    the conv output and the BN, so moving the BN affine before it changes
    results (r3 ADVICE; the reference oneDNN pass only folds bare conv->BN)."""
    rng = onp.random.RandomState(5)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4,
                            activation="relu"),
            gluon.nn.BatchNorm(in_channels=8))
    net.initialize()
    x = np.array(rng.uniform(-1, 1, (2, 4, 8, 8)).astype("float32"))
    net(x)
    for name, p in net.collect_params().items():
        if "running_mean" in name or "beta" in name:
            p.set_data(np.array(rng.uniform(-0.5, 0.5,
                                            p.shape).astype("float32")))
        if "running_var" in name or "gamma" in name:
            p.set_data(np.array(rng.uniform(0.5, 2.0,
                                            p.shape).astype("float32")))
    ref = net(x).asnumpy()
    assert q.fold_conv_bn(net) == 0                  # skipped, not folded
    assert type(net._children["1"]) is gluon.nn.BatchNorm
    onp.testing.assert_allclose(net(x).asnumpy(), ref, rtol=0, atol=0)


def test_requantize_chain_matches_unchained():
    """conv-bn-relu-conv chain: quantize_net with fold_bn+requantize stays
    within int8 error of fp32 and chains the two convs through int8 (the
    producer emits int8). Checkpoint round-trip of chained nets is covered
    by test_chained_net_save_load_roundtrip."""
    rng = onp.random.RandomState(1)
    def build():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4),
                gluon.nn.BatchNorm(in_channels=8),
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2D(8, 3, padding=1, in_channels=8))
        net.initialize()
        return net

    net = build()
    x = np.array(rng.uniform(-1, 1, (4, 4, 8, 8)).astype("float32"))
    net(x)
    ref = net(x).asnumpy()
    q.quantize_net(net, calib_data=[x], calib_mode="naive")
    conv1 = net._children["0"]
    conv2 = net._children["3"]
    assert type(conv1) is q.QuantizedConv2D
    assert type(conv2) is q.QuantizedConv2D
    assert conv1._out_threshold is conv2.qthreshold  # chained, shared param
    out = net(x).asnumpy()
    assert out.dtype == onp.float32  # last layer still emits f32
    rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6)
    assert rel < 0.06, rel
    # requantize=False must leave the producer unchained (f32 between layers)
    net2 = build()
    net2(x)
    q.quantize_net(net2, calib_data=[x], calib_mode="naive",
                   requantize=False)
    assert net2._children["0"]._out_threshold is None


def test_chain_skips_non_relu_fused_activation():
    """A producer with a fused sigmoid must NOT be requantize-chained: the
    int8 emit happens before self.act, and sigmoid over int8 CODES is
    garbage. relu-fused producers chain fine."""
    rng = onp.random.RandomState(5)
    x = np.array(rng.uniform(-1, 1, (8, 16)).astype("float32"))
    for act, chained in (("sigmoid", False), ("relu", True), (None, True)):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, in_units=16, activation=act),
                gluon.nn.Dense(4, in_units=32))
        net.initialize()
        ref = net(x).asnumpy()
        q.quantize_net(net, calib_data=[x], calib_mode="naive")
        assert (net._children["0"]._out_threshold is not None) == chained, act
        out = net(x).asnumpy()
        rel = onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6)
        assert rel < 0.06, (act, rel)


def test_fold_conv_bn_preserves_weight_dtype():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4),
            gluon.nn.BatchNorm(in_channels=8))
    net.initialize()
    net(np.array(onp.zeros((1, 4, 8, 8), "float32")))
    net.cast("bfloat16")
    assert q.fold_conv_bn(net) == 1
    assert onp.dtype(net._children["0"].weight.data().dtype) == "bfloat16"


def test_dropout_p_one_returns_zeros():
    from incubator_mxnet_tpu import npx
    z = npx.dropout(np.array(onp.ones((16, 128), "float32")),
                    p=1.0, mode="always")
    assert float(onp.abs(z.asnumpy()).max()) == 0.0


def test_chained_net_save_load_roundtrip(tmp_path):
    """save_parameters/load_parameters round-trip of a requantize-CHAINED
    net: the shared out-threshold must not double-register (no duplicate
    checkpoint key), and a freshly-quantized same-structure net must load
    the checkpoint and reproduce outputs exactly."""
    rng = onp.random.RandomState(9)

    def build_q(calib):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4),
                gluon.nn.BatchNorm(in_channels=8),
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2D(8, 3, padding=1, in_channels=8))
        net.initialize()
        net(calib)
        q.quantize_net(net, calib_data=[calib], calib_mode="naive")
        return net

    x = np.array(rng.uniform(-1, 1, (4, 4, 8, 8)).astype("float32"))
    net = build_q(x)
    # the chained producer must NOT register the shared threshold under
    # its own name (no '_out_threshold' key, no renamed parameter)
    keys = list(net.collect_params())
    assert not any("_out_threshold" in k for k in keys), keys
    out = net(x).asnumpy()
    f = str(tmp_path / "chained.params")
    net.save_parameters(f)
    net2 = build_q(x)  # different init/calib; structure identical
    net2.load_parameters(f)
    onp.testing.assert_allclose(net2(x).asnumpy(), out,
                                rtol=1e-5, atol=1e-6)


def test_chained_bf16_net_keeps_dtype():
    """In a bf16 net, the LAST layer of an int8 chain must emit bf16 (the
    net's activation dtype), not hardcoded f32."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, in_units=16, activation="relu"),
            gluon.nn.Dense(16, in_units=32))
    net.initialize()
    net.cast("bfloat16")
    x = np.array(onp.random.RandomState(3)
                 .uniform(-1, 1, (4, 16)).astype("float32")).astype("bfloat16")
    net(x)
    q.quantize_net(net, calib_data=[x], calib_mode="naive")
    assert net._children["0"]._out_threshold is not None  # chained
    out = net(x)
    assert onp.dtype(out.dtype) == onp.dtype("bfloat16"), out.dtype


def test_residual_chain_int8_fidelity():
    """V1 residual blocks chain int8 through the add (VERDICT r3 #3):
    the chained net must (a) actually wrap the blocks, (b) track the
    fp32 reference about as well as the unchained int8 net, (c) keep
    top-1 agreement with fp32 on random inputs."""
    import numpy as onp

    from incubator_mxnet_tpu import np
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet18_v1

    rng = onp.random.RandomState(0)
    x = np.array(rng.uniform(-1, 1, (8, 3, 64, 64)).astype("float32"))
    calib = [x[:4]]

    def build(chain):
        net = resnet18_v1(classes=10)
        mx.random.seed(3)
        net.initialize()
        net(x[:1])
        q.quantize_net(net, calib_data=calib, calib_mode="naive",
                       chain_residual=chain)
        return net

    fp32 = resnet18_v1(classes=10)
    mx.random.seed(3)
    fp32.initialize()
    ref = fp32(x).asnumpy()

    unchained = build(False)(x).asnumpy()
    chained_net = build(True)
    n_wrapped = sum(1 for b in _walk_blocks(chained_net)
                    if type(b).__name__ == "QuantizedResidualBlock")
    assert n_wrapped >= 8, n_wrapped          # resnet18: 8 basic blocks
    chained = chained_net(x).asnumpy()

    def cos(a, b):
        a, b = a.ravel(), b.ravel()
        return float(a @ b / (onp.linalg.norm(a) * onp.linalg.norm(b)
                              + 1e-12))

    c_un = cos(ref, unchained)
    c_ch = cos(ref, chained)
    assert c_ch > 0.98, (c_ch, c_un)
    assert c_ch > c_un - 0.02, (c_ch, c_un)   # no material fidelity loss
    # top-1 agreement with fp32
    agree = (chained.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.75, agree


def _walk_blocks(net):
    out = []
    stack = [net]
    while stack:
        b = stack.pop()
        out.append(b)
        stack.extend(c for c in b._children.values()
                     if hasattr(c, "_children"))
    return out


def test_quantize_symmetric_jax_roundtrip():
    """The jax-side twin of _quantize_weight (ISSUE 6: int8 KV pages):
    per-group symmetric ±127 quantization round-trips within the one-LSB
    bound, and an imposed (grow-only page) scale is honored."""
    import jax.numpy as jnp

    rng = onp.random.RandomState(0)
    x = jnp.asarray(rng.normal(0, 2.0, (4, 8, 16, 32)).astype("float32"))
    qv, scale = q.quantize_symmetric(x, axes=(2, 3))
    assert qv.dtype == jnp.int8 and scale.shape == (4, 8, 1, 1)
    back = q.dequantize_symmetric(qv, scale)
    err = float(jnp.max(jnp.abs(back - x)))
    assert err <= float(jnp.max(scale)) * 0.5 + 1e-6   # half-LSB rounding
    # imposed scale (requantization into an existing page's scale)
    qv2, s2 = q.quantize_symmetric(x, axes=(), scale=scale * 2)
    assert float(jnp.max(jnp.abs(qv2.astype(jnp.float32)))) <= 127
    back2 = q.dequantize_symmetric(qv2, s2)
    assert float(jnp.max(jnp.abs(back2 - x))) <= float(jnp.max(s2))
