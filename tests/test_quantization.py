"""INT8 quantization: calibration algorithms, quantized op numerics, and
end-to-end accuracy preservation (reference:
`tests/python/quantization/test_quantization.py`, accuracy discipline from
`example/quantization/README.md` — ≤0.5% top-1 drop)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, np
from incubator_mxnet_tpu.contrib import quantization as q


def test_entropy_threshold_clips_outliers():
    """A gaussian bulk with a far outlier: entropy calibration should pick
    a threshold well below the outlier; naive picks the outlier."""
    rng = onp.random.RandomState(0)
    x = onp.abs(rng.randn(100000)).astype("float32")
    x[0] = 50.0  # outlier
    hist, edges = onp.histogram(onp.abs(x), bins=2048, range=(0, 50.0))
    t = q.optimal_threshold_entropy(hist, edges)
    assert t < 25.0, t
    assert t > 1.0, t


def test_quantized_dense_matches_fp32():
    rng = onp.random.RandomState(1)
    dense = gluon.nn.Dense(32, in_units=16)
    dense.initialize()
    x = np.array(rng.uniform(-2, 2, (8, 16)).astype("float32"))
    ref = dense(x).asnumpy()
    qd = q.QuantizedDense(dense, threshold=2.0)
    out = qd(x).asnumpy()
    # int8 quantization error bound: ~1% relative on well-scaled data
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.03


def test_quantized_conv_matches_fp32():
    rng = onp.random.RandomState(2)
    conv = gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=4)
    conv.initialize()
    x = np.array(rng.uniform(-1, 1, (2, 4, 12, 12)).astype("float32"))
    ref = conv(x).asnumpy()
    qc = q.QuantizedConv2D(conv, threshold=1.0)
    out = qc(x).asnumpy()
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.03


def _make_toy_problem(n=512, seed=0):
    """Linearly-separable-ish 4-class problem through a small conv net."""
    rng = onp.random.RandomState(seed)
    X = rng.uniform(-1, 1, (n, 3, 8, 8)).astype("float32")
    # class = argmax of 4 fixed random projections -> learnable
    W = rng.randn(4, 3 * 8 * 8).astype("float32")
    Y = (X.reshape(n, -1) @ W.T).argmax(1).astype("int32")
    return X, Y


def _accuracy(net, X, Y, bs=64):
    correct = 0
    for i in range(0, len(X), bs):
        out = net(np.array(X[i:i + bs]))
        correct += int((out.asnumpy().argmax(1) == Y[i:i + bs]).sum())
    return correct / len(X)


def test_quantize_net_end_to_end_accuracy():
    """Train fp32 -> quantize (entropy calib) -> accuracy drop must stay
    within the reference's discipline (≤0.5% on ImageNet-scale calib; a
    4-batch toy calibration is noisier, so ≤2% here)."""
    mx.random.seed(7)
    X, Y = _make_toy_problem()
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, in_channels=3,
                            activation="relu"),
            gluon.nn.Conv2D(16, 3, padding=1, in_channels=16,
                            activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(12):
        for i in range(0, len(X), 64):
            xb, yb = np.array(X[i:i + 64]), np.array(Y[i:i + 64])
            with autograd.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
    acc_fp32 = _accuracy(net, X, Y)
    assert acc_fp32 > 0.8, f"fp32 net failed to train: {acc_fp32}"
    ref_out = net(np.array(X[:8])).asnumpy()

    calib = [np.array(X[i:i + 64]) for i in range(0, 256, 64)]
    q.quantize_net(net, calib_data=calib, calib_mode="entropy",
                   num_calib_batches=4)
    # every Dense/Conv must have been swapped — in _children AND in the
    # Sequential._layers list that forward() actually iterates
    assert all(type(c) in (q.QuantizedConv2D, q.QuantizedDense)
               for c in net._children.values())
    assert all(type(c) in (q.QuantizedConv2D, q.QuantizedDense)
               for c in net._layers)
    # and they must actually execute: int8 output differs from fp32
    assert not onp.array_equal(net(np.array(X[:8])).asnumpy(), ref_out)
    acc_int8 = _accuracy(net, X, Y)
    assert acc_fp32 - acc_int8 <= 0.02, (acc_fp32, acc_int8)


def test_quantize_hybridized_net_and_save_load(tmp_path):
    """Quantizing an already-hybridized (and traced) net must re-trace the
    quantized graph, and the quantized net must round-trip through
    save_parameters/load_parameters (weights live in Constant params)."""
    rng = onp.random.RandomState(3)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    x = np.array(rng.uniform(-1, 1, (4, 8)).astype("float32"))
    ref = net(x).asnumpy()           # builds the fp32 cached graph
    q.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = net(x).asnumpy()           # must NOT replay the stale fp32 graph
    assert not onp.array_equal(out, ref)
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.05

    f = str(tmp_path / "qnet.params")
    net.save_parameters(f)
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(16, in_units=8, activation="relu"),
             gluon.nn.Dense(4, in_units=16))
    net2.initialize()
    q.quantize_net(net2, calib_mode="none")   # same structure, wrong params
    net2.load_parameters(f)
    assert_close = onp.testing.assert_allclose
    assert_close(net2(x).asnumpy(), out, rtol=1e-5, atol=1e-5)


def test_quantize_net_exclude_and_naive():
    X, _ = _make_toy_problem(64)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, in_units=192, activation="relu"),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    ref = net(np.array(X[:4].reshape(4, -1))).asnumpy()
    calib = [np.array(X[:32].reshape(32, -1))]
    q.quantize_net(net, calib_data=calib, calib_mode="naive",
                   exclude_layers_match=[r"^1$"])
    assert type(net._children["0"]) is q.QuantizedDense
    assert type(net._children["1"]) is gluon.nn.Dense  # excluded stays fp32
    # the swapped layer must actually execute: output differs from fp32
    # but stays within int8 error
    out = net(np.array(X[:4].reshape(4, -1))).asnumpy()
    assert not onp.array_equal(out, ref)
    assert onp.abs(out - ref).max() / (onp.abs(ref).max() + 1e-6) < 0.05


def test_quantize_requires_calib_data():
    # a net with no quantizable layers is a no-op, not an error
    q.quantize_net(gluon.nn.HybridSequential(), calib_mode="entropy")
    # but a net WITH layers must demand calibration data
    net2 = gluon.nn.HybridSequential()
    net2.add(gluon.nn.Dense(4, in_units=8))
    net2.initialize()
    with pytest.raises(ValueError):
        q.quantize_net(net2, calib_mode="entropy")
