"""MXNET_* configuration knobs (reference: ~80 vars in env_var.md read
via dmlc::GetEnv; SURVEY §5.6). Covers the honored set end-to-end with
`test_utils.environment` scoping."""
import logging

import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import np, npx, util
from incubator_mxnet_tpu.test_utils import environment


def test_env_knobs_table_is_complete():
    knobs = util.env_knobs()
    assert len(knobs) >= 40
    honored = [k for k, (how, _) in knobs.items()
               if not how.startswith("(")]
    assert len(honored) >= 20
    # every entry documents both a mechanism and a description
    for k, (how, doc) in knobs.items():
        assert k.startswith("MXNET_") and how and doc


def test_safe_accumulation_softmax():
    x16 = np.array(onp.random.RandomState(0)
                   .uniform(-1, 1, (4, 8)).astype("float16"))
    with environment("MXNET_SAFE_ACCUMULATION", "1"):
        out = npx.softmax(x16, axis=-1)
    assert str(out.dtype) == "float16"          # cast back after fp32 acc
    onp.testing.assert_allclose(out.asnumpy().sum(-1),
                                onp.ones(4), rtol=1e-2)
    with environment("MXNET_SAFE_ACCUMULATION", "1"):
        n = npx.norm(x16, ord=2)
    assert str(n.dtype) == "float16"


def test_worker_nthreads_aliases():
    from incubator_mxnet_tpu.util import default_num_workers

    with environment("MXNET_CPU_WORKER_NTHREADS", "3"):
        assert default_num_workers() == 3
    with environment({"MXNET_CPU_WORKER_NTHREADS": None,
                      "MXNET_MP_WORKER_NTHREADS": "2"}):
        assert default_num_workers() == 2
    with environment({"MXNET_CPU_WORKER_NTHREADS": None,
                      "MXNET_MP_WORKER_NTHREADS": None}):
        assert default_num_workers() == 0


def test_update_on_kvstore_default():
    from incubator_mxnet_tpu import gluon

    net = gluon.nn.Dense(4)
    net.initialize()
    with environment("MXNET_UPDATE_ON_KVSTORE", "1"):
        t = gluon.Trainer(net.collect_params(), "sgd")
    assert t._update_on_kvstore is True
    t2 = gluon.Trainer(net.collect_params(), "sgd",
                       update_on_kvstore=False)
    assert t2._update_on_kvstore is False


def test_storage_fallback_log(caplog):
    from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray

    vals = onp.ones((2, 3), "float32")
    idx = onp.array([0, 2], "int32")
    with environment("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", "1"):
        rs = RowSparseNDArray(vals, idx, (4, 3))
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.sparse"):
            rs.asnumpy()                        # densifies
    assert any("storage fallback" in r.message for r in caplog.records)


def test_optimizer_aggregation_size_disables_fusion():
    """0/1 must turn the fused small-parameter path off; the step still
    trains correctly."""
    from incubator_mxnet_tpu import autograd, gluon, optimizer
    from incubator_mxnet_tpu.parallel.sharded import DataParallel

    def run():
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(4, activation="relu"), gluon.nn.Dense(2))
        net.initialize()
        x = np.array(onp.random.RandomState(0)
                     .uniform(-1, 1, (8, 6)).astype("float32"))
        y = np.array(onp.random.RandomState(1)
                     .randint(0, 2, (8,)).astype("int32"))
        net(x)
        dp = DataParallel(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          optimizer.Adam(learning_rate=0.01))
        return float(dp.step(x, y).asnumpy())

    mx.random.seed(0)
    base = run()
    mx.random.seed(0)
    with environment("MXNET_OPTIMIZER_AGGREGATION_SIZE", "0"):
        off = run()
    onp.testing.assert_allclose(base, off, rtol=1e-5)


def test_gluon_repo_root_searched():
    import os
    import shutil
    import tempfile

    from incubator_mxnet_tpu.gluon.model_zoo import model_store

    src_root = os.path.join(os.path.dirname(model_store.__file__),
                            "_store")
    names = model_store._load_registry(src_root)
    if not names:
        pytest.skip("no packaged artifact to relocate")
    name = next(iter(names))
    with tempfile.TemporaryDirectory() as d:
        shutil.copytree(src_root, os.path.join(d, "store"))
        with environment("MXNET_GLUON_REPO", os.path.join(d, "store")):
            path = model_store.get_model_file(name)
        assert path.startswith(os.path.join(d, "store"))


def test_library_path_search(tmp_path):
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run(["make", "-C", os.path.join(repo, "src")], check=True,
                   capture_output=True)
    from incubator_mxnet_tpu import library

    with environment("MXNET_LIBRARY_PATH", os.path.join(repo, "build")):
        ops = library.load("libexample_ext.so", verbose=False)
    assert "my_relu" in ops


def test_profiler_mode_symbolic_only():
    import subprocess
    import sys

    code = (
        "import incubator_mxnet_tpu as mx\n"
        "from incubator_mxnet_tpu import profiler\n"
        "print('imperative', profiler._CONFIG['profile_imperative'])\n")
    out = subprocess.run(
        [sys.executable, "-c", code],
        env={**__import__('os').environ,
             "MXNET_PROFILER_AUTOSTART": "1",
             "MXNET_PROFILER_MODE": "0",
             "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300)
    assert "imperative False" in out.stdout, out.stderr[-500:]
